"""LT008 — resource lifecycle: every path must discharge the obligation.

The PR-7 review found two of these by hand: a ``Run`` configured with
``shared_cache=True`` could build an ingest store it never attached (so
nothing ever closed it), and a server teardown ordering bug left the
process-global cache pointing at a closed store.  The class is general:
an object whose type carries a ``close``/``stop``/``shutdown``/``join``
obligation is created on one line, and *some* path — usually the
exception path nobody tests — exits without discharging it.  Leaked
mmaps/fds keep segment files pinned past eviction, leaked executors keep
non-daemon threads alive past the run, and a leaked fault plan poisons
the next run in the process.

Tracked resources:

* stdlib constructors — ``open`` (outside ``with``), ``mmap.mmap``,
  ``ThreadPoolExecutor``, non-daemon ``threading.Thread``,
  ``threading.Timer``, ``subprocess.Popen``, ``socket.socket``;
* **project classes that define** ``close``/``stop``/``shutdown`` —
  resolved through :mod:`.callgraph`'s class index, so ``BlockStore``,
  ``EventLog``, ``Telemetry``, the metrics exporter/server and the
  serve-layer objects are all first-class.

Per creation, a path-sensitive walk of the creating function checks:

* **local ownership** (the function later calls the obligation method on
  the name): every normal exit must have discharged — discharge inside
  an ``if`` whose test mentions the name counts for the whole branch
  point (the ``if x is not None: x.stop()`` idiom) — and every
  may-raise statement executed while the resource is live must sit
  inside a ``try`` whose handler or ``finally`` discharges it
  (directly, or for ``self.`` attributes via a same-class method that
  transitively discharges — ``except BaseException:
  self._shutdown_shared()`` counts).  "May raise" means any call not on
  the infallible-builtin whitelist, so the finding reads "leaks if line
  N raises before the owning try/finally" — the exact shape of the PR-7
  constructor bugs;
* **escape** (returned, yielded, passed to a callee, stored into a
  container or another object) transfers ownership and ends local
  tracking — except a ``self.attr`` store, which converts the obligation
  to the **class level**: somewhere in the project an obligation method
  must be invoked on that attribute (``anything.attr.close()``); a
  module-``global`` store likewise requires a discharge site in the same
  module.  This is deliberately name-based — it cannot prove the close
  runs, only that one *exists*; a store nobody closes anywhere is the
  PR-7 bug with no false-positive risk;
* a creation that is never discharged, never escapes, and never enters a
  ``with`` is a certain leak, reported unconditionally.

Scope: ``land_trendr_tpu/`` only (plus bare fixture files).  Tools and
tests are process-scoped — their resources die with the interpreter —
and fixtures model leaks on purpose.
"""

from __future__ import annotations

import ast
from typing import Iterator

from land_trendr_tpu.lintkit.callgraph import _terminal_name, get_graph
from land_trendr_tpu.lintkit.core import Checker, Finding, RepoCtx

__all__ = ["ResourceLifecycleChecker"]

#: obligation methods that discharge a tracked resource
_OBLIGATIONS = frozenset(
    {"close", "stop", "shutdown", "join", "terminate", "kill", "cancel", "wait"}
)

#: class-defining methods that make a project class a tracked resource
_RESOURCE_DEFS = ("close", "stop", "shutdown")

#: stdlib constructor name -> human label
_BUILTIN_CTORS = {
    "ThreadPoolExecutor": "executor",
    "Popen": "subprocess",
    "Timer": "timer",
}

#: calls that cannot realistically raise — they do not count as
#: "may raise before the owning try/finally"
_INFALLIBLE = frozenset(
    {
        "deque", "list", "dict", "set", "tuple", "frozenset", "min", "max",
        "len", "sorted", "int", "float", "str", "bool", "round", "abs",
        "enumerate", "range", "zip", "iter", "getattr", "hasattr",
        "isinstance", "id", "repr", "format", "perf_counter", "monotonic",
        "time", "append", "appendleft", "popleft", "pop", "add", "discard",
        "info", "warning", "error", "debug", "exception", "critical", "get",
        "items", "keys", "values", "join", "split", "strip", "startswith",
        "endswith", "rstrip", "lstrip", "copy", "setdefault", "update",
        "field", "dataclass", "is_set", "astype",
    }
)


def _names_in(expr: ast.AST) -> set:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _shallow_walk(node: ast.AST):
    """``ast.walk`` that does not descend into nested function bodies —
    a closure's statements run when it is CALLED, not where it is
    defined."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


class _Resource:
    """One tracked creation inside one function."""

    def __init__(self, label: str, line: int, target: "tuple | None") -> None:
        self.label = label  # "BlockStore 'store'" for messages
        self.line = line
        #: ("name", x) local binding | ("attr", y) self.y | None (bare)
        self.target = target

    def is_expr(self, expr: ast.AST) -> bool:
        """Does ``expr`` denote this resource?"""
        if self.target is None:
            return False
        kind, name = self.target
        if kind == "name":
            return isinstance(expr, ast.Name) and expr.id == name
        return (
            isinstance(expr, ast.Attribute)
            and expr.attr == name
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        )

    def name_token(self) -> str:
        return self.target[1] if self.target else ""


class ResourceLifecycleChecker(Checker):
    rule_id = "LT008"
    title = "resource created but not discharged on every path"

    def inputs(self, repo: RepoCtx) -> "set[str] | None":
        return {f for f in repo.py_files if not f.startswith("tests/")}

    # -- project-level indexes --------------------------------------------
    def _project_state(self, repo: RepoCtx) -> dict:
        graph = get_graph(repo)
        state = repo.cache.get("lifecycle")
        if state is not None:
            return state
        # project classes that ARE resources: own/inherited close/stop/...
        resource_classes: dict[str, str] = {}
        for cname, entries in graph.class_files.items():
            for file, _node in entries:
                for meth in _RESOURCE_DEFS:
                    if (file, cname, meth) in graph.class_methods:
                        resource_classes.setdefault(cname, meth)
        # attrs with a discharge site anywhere: .attr.<obl>() call
        discharged_attrs: set = set()
        # module-global names with a discharge site, per file
        discharged_globals: dict[str, set] = {}
        # (cls qname-prefix) methods that transitively discharge attr y
        attr_discharging_methods: dict[tuple, set] = {}
        for relpath in repo.py_files:
            if relpath.startswith("tests/"):
                continue
            ctx = repo.file(relpath)
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _OBLIGATIONS
                ):
                    continue
                recv = node.func.value
                if isinstance(recv, ast.Attribute):
                    discharged_attrs.add(recv.attr)
                elif isinstance(recv, ast.Name):
                    discharged_globals.setdefault(relpath, set()).add(recv.id)
        # alias-aware global discharge: `old = _pool; ...; old.shutdown()`
        # (the resize idiom) discharges the global it was read from
        for relpath, names in list(discharged_globals.items()):
            ctx = repo.file(relpath)
            if ctx.tree is None:
                continue
            aliases: dict[str, str] = {}
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases[t.id] = node.value.id
            for name in list(names):
                if name in aliases:
                    names.add(aliases[name])
        # same-class methods that discharge self.<y>: one AST pass
        # collecting per-method facts (direct attr discharges + self
        # calls), then a table-only propagation — no re-walking
        self_calls: dict[tuple, set] = {}
        for (file, cls, meth), qname in graph.class_methods.items():
            info = graph.funcs.get(qname)
            if info is None:
                continue
            calls = self_calls.setdefault((file, cls, meth), set())
            for node in ast.walk(info.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                recv = node.func.value
                if (
                    node.func.attr in _OBLIGATIONS
                    and isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                ):
                    attr_discharging_methods.setdefault(
                        (file, cls, recv.attr), set()
                    ).add(meth)
                elif isinstance(recv, ast.Name) and recv.id == "self":
                    calls.add(node.func.attr)
        for _ in range(2):  # two hops: __init__ guard -> teardown -> close
            for (file, cls, meth), calls in self_calls.items():
                for (f2, c2, attr), meths in attr_discharging_methods.items():
                    if f2 == file and c2 == cls and calls & meths:
                        meths.add(meth)
        state = repo.cache["lifecycle"] = {
            "graph": graph,
            "resource_classes": resource_classes,
            "discharged_attrs": discharged_attrs,
            "discharged_globals": discharged_globals,
            "attr_methods": attr_discharging_methods,
        }
        return state

    # -- creation recognition ---------------------------------------------
    def _ctor_label(self, graph, mod, call: ast.Call) -> "str | None":
        """Human label when ``call`` constructs a tracked resource."""
        name = _terminal_name(call.func)
        base = (
            call.func.value.id
            if isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            else None
        )
        if name == "open" and base in (None, "io"):
            return "open() handle"
        if name == "mmap" and base == "mmap":
            return "mmap"
        if name == "socket" and base == "socket":
            return "socket"
        if name == "Thread":
            daemon = next(
                (kw.value for kw in call.keywords if kw.arg == "daemon"), None
            )
            if isinstance(daemon, ast.Constant) and daemon.value is True:
                return None  # daemon threads die with the process
            return "thread"
        if name in _BUILTIN_CTORS:
            return _BUILTIN_CTORS[name]
        state = self._state
        cls = graph._resolve_class_name(mod, call.func)
        if cls is not None and cls in state["resource_classes"]:
            return f"{cls} (has .{state['resource_classes'][cls]}())"
        return None

    # -- the rule ----------------------------------------------------------
    def check(self, repo: RepoCtx) -> Iterator[Finding]:
        self._state = self._project_state(repo)
        graph = self._state["graph"]
        for info in graph.functions():
            file = info.file
            in_scope = file.startswith("land_trendr_tpu/") or "/" not in file
            if not in_scope or file.startswith("tests/"):
                continue
            yield from self._check_function(graph, info)

    def _check_function(self, graph, info) -> Iterator[Finding]:
        # the outer function and each nested def are separate walks: a
        # resource created AND discharged inside a closure belongs to
        # the closure's own statement tree (collecting its creation at
        # the outer level while walking only outer statements reported
        # phantom "certain leak"s)
        for fn in self._fn_and_nested(info.node):
            yield from self._check_one_scope(graph, info, fn)

    @staticmethod
    def _fn_and_nested(fn: ast.AST):
        out = [fn]
        for node in ast.walk(fn):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fn
            ):
                out.append(node)
        return out

    def _check_one_scope(self, graph, info, fn) -> Iterator[Finding]:
        mod = graph.modules[info.file]
        symbol = f"{info.cls}.{info.name}" if info.cls else info.name
        global_names = {
            n
            for node in _shallow_walk(fn)
            if isinstance(node, ast.Global)
            for n in node.names
        }
        # with-managed and immediately-chained creations are discharged
        with_ctx: set = set()
        for node in _shallow_walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    with_ctx.add(id(item.context_expr))

        for node in _shallow_walk(fn):
            if not isinstance(node, ast.Assign) and not isinstance(node, ast.Expr):
                continue
            value = node.value
            if isinstance(value, ast.IfExp):
                # `self.x = Ctor(...) if flag else None` — the common
                # optional-subsystem idiom: track the constructing arm
                value = (
                    value.body
                    if isinstance(value.body, ast.Call)
                    else value.orelse
                )
            call = None
            if isinstance(value, ast.Call):
                call = value
                # the `X(...).start()` chain: the ctor is the receiver
                if (
                    self._ctor_label(graph, mod, call) is None
                    and isinstance(value.func, ast.Attribute)
                    and isinstance(value.func.value, ast.Call)
                ):
                    call = value.func.value
            if call is None or id(call) in with_ctx:
                continue
            label = self._ctor_label(graph, mod, call)
            if label is None:
                continue
            if isinstance(node, ast.Expr):
                # constructed, never bound: nothing can ever discharge it
                yield Finding(
                    info.file, node.lineno, self.rule_id,
                    f"{label} constructed but never bound — no path can "
                    "discharge its close/stop/shutdown obligation",
                    symbol=symbol,
                )
                continue
            target = None
            for t in node.targets:
                if isinstance(t, ast.Name):
                    kind = "global" if t.id in global_names else "name"
                    target = (kind, t.id)
                    break
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    target = ("attr", t.attr)
                    break
            if target is None:
                continue  # stored into a container: ownership transferred
            yield from self._check_creation(
                graph, info, fn, symbol, label, node, target
            )

    def _check_creation(
        self, graph, info, fn, symbol, label, assign, target
    ) -> Iterator[Finding]:
        state = self._state
        kind, name = target
        if kind == "global":
            # module-owned singleton: a discharge site must exist in the
            # same module (process-wide pools are reconfigured there)
            sites = state["discharged_globals"].get(info.file, set())
            if name not in sites:
                yield Finding(
                    info.file, assign.lineno, self.rule_id,
                    f"{label} stored to module global '{name}' but this "
                    f"module never calls an obligation method on it — "
                    "the resource outlives every owner",
                    symbol=symbol,
                )
            return
        res = _Resource(
            f"{label} '{'self.' if kind == 'attr' else ''}{name}'",
            assign.lineno,
            ("attr", name) if kind == "attr" else ("name", name),
        )
        walker = _Walker(self, graph, info, fn, res, assign)
        walker.run()
        if kind == "attr":
            # class-level obligation: SOME discharge site must exist
            if name not in state["discharged_attrs"]:
                yield Finding(
                    info.file, assign.lineno, self.rule_id,
                    f"{res.label} stored but no '.{name}.<close/stop/"
                    "shutdown>()' call exists anywhere in the project — "
                    "nothing ever discharges it (the PR-7 unattached-"
                    "store class)",
                    symbol=symbol,
                )
            # exception path within the creating function still applies
            if walker.exc_leak is not None:
                yield Finding(
                    info.file, assign.lineno, self.rule_id,
                    f"{res.label} leaks if line {walker.exc_leak} raises: "
                    "the statements after the store are not guarded by a "
                    "try whose handler/finally discharges it",
                    symbol=symbol,
                )
            return
        # local binding
        if walker.escaped and not walker.discharges:
            return  # ownership transferred wholesale
        if not walker.discharges and not walker.escaped:
            yield Finding(
                info.file, assign.lineno, self.rule_id,
                f"{res.label} is never closed, never escapes, and is not "
                "a context manager here — a certain leak on every path",
                symbol=symbol,
            )
            return
        if walker.normal_leak:
            yield Finding(
                info.file, assign.lineno, self.rule_id,
                f"{res.label} is not discharged on every normal path "
                "(a branch returns/falls through with it live) — use "
                "try/finally or `with`",
                symbol=symbol,
            )
        if walker.exc_leak is not None:
            yield Finding(
                info.file, assign.lineno, self.rule_id,
                f"{res.label} leaks if line {walker.exc_leak} raises "
                "before the owning try/finally — move the creation "
                "inside the try (or guard the gap with except "
                "BaseException: discharge; raise)",
                symbol=symbol,
            )


class _Walker:
    """Path-sensitive walk of one function for one resource.

    States are sets drawn from {"unborn", "live", "done"}; statements
    map state sets to state sets, branches union, ``try`` handlers see
    every state the body could be in, and a discharging ``finally``
    (or handler) makes the gap between creation and the try safe.
    """

    def __init__(
        self, checker, graph, info, fn, res: _Resource, assign
    ) -> None:
        self.checker = checker
        self.graph = graph
        self.info = info
        self.fn = fn  # the scope being walked (outer fn OR a nested def)
        self.res = res
        self.assign = assign
        self.discharges = False  # any obligation call on the resource
        self.escaped = False
        self.normal_leak = False
        self.exc_leak: "int | None" = None
        #: the function discharges this resource SOMEWHERE: it owns it,
        #: so handing the name to a callee is a share, not a transfer —
        #: escapes stop ending the walk and the exception-path analysis
        #: stays armed (the driver stores the ingest store into the
        #: process-global cache AND closes it in its finally: owned)
        self.owned = False
        #: nested defs whose body discharges this resource: a handler
        #: calling `_release_setup()` counts as discharging everything
        #: that closure releases (the telescoping-unwind idiom)
        self._discharging_locals: set = set()
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if sub is fn:
                    continue
                for n in ast.walk(sub):
                    if (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in _OBLIGATIONS
                        and res.is_expr(n.func.value)
                    ):
                        self._discharging_locals.add(sub.name)
                        break

    # -- classification helpers -------------------------------------------
    def _is_discharge(self, node: ast.AST) -> bool:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _OBLIGATIONS
            and self.res.is_expr(node.func.value)
        ):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in self._discharging_locals
        ):
            return True
        # self._teardown() that transitively discharges self.<attr>
        if (
            self.res.target
            and self.res.target[0] == "attr"
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
            and self.info.cls is not None
        ):
            meths = self.checker._state["attr_methods"].get(
                (self.info.file, self.info.cls, self.res.target[1]), set()
            )
            if node.func.attr in meths:
                return True
        return False

    def _block_discharges(self, stmts: list) -> bool:
        for stmt in stmts:
            for node in _shallow_walk(stmt):
                if self._is_discharge(node):
                    return True
        return False

    def _carries_resource(self, expr: ast.AST, name: str) -> bool:
        """Does a returned/yielded expression hand the HANDLE out?
        ``return fh`` / ``return (a, fh)`` / ``return wrap(fh)`` do;
        ``return fh.read()`` returns derived data — the receiver of a
        method call is not ownership transfer."""
        if isinstance(expr, ast.Name):
            return expr.id == name
        if isinstance(expr, ast.Attribute):
            return False
        if isinstance(expr, ast.Call):
            return any(
                self._carries_resource(a, name) for a in expr.args
            ) or any(
                self._carries_resource(kw.value, name)
                for kw in expr.keywords
            )
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self._carries_resource(e, name) for e in expr.elts)
        if isinstance(expr, ast.Dict):
            return any(
                self._carries_resource(v, name) for v in expr.values
            )
        if isinstance(expr, ast.IfExp):
            return self._carries_resource(
                expr.body, name
            ) or self._carries_resource(expr.orelse, name)
        return name in _names_in(expr)  # odd shapes: stay conservative

    def _stmt_escapes(self, stmt: ast.AST) -> bool:
        """The resource's NAME leaves this function's ownership."""
        if self.res.target is None or self.res.target[0] != "name":
            return False
        name = self.res.name_token()
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if node.value is not None and self._carries_resource(
                    node.value, name
                ):
                    return True
            elif isinstance(node, ast.Call):
                if self._is_discharge(node):
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id == name:
                        return True
            elif isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Name) and node.value.id == name:
                    return True
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # captured by a closure: lifetime leaves this walk
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
        return False

    def _may_raise(self, stmt: ast.AST) -> bool:
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            return True
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False  # a def itself does not run its body
        # compound statements recurse through _walk_inner, where their
        # bodies see the right protection context — only the HEADER
        # expression is evaluated at this level
        if isinstance(stmt, (ast.If, ast.While)):
            exprs: list = [stmt.test]
        elif isinstance(stmt, ast.For):
            exprs = [stmt.iter]
        elif isinstance(stmt, ast.With):
            exprs = [item.context_expr for item in stmt.items]
        elif isinstance(stmt, ast.Try):
            return False
        else:
            exprs = [stmt]
        for expr in exprs:
            for node in _shallow_walk(expr):
                if isinstance(node, ast.Call):
                    if self._is_discharge(node):
                        continue
                    if _terminal_name(node.func) not in _INFALLIBLE:
                        return True
        return False

    def _is_daemon_mark(self, stmt: ast.AST) -> bool:
        """``x.daemon = True`` — a daemon thread/timer dies with the
        process; the join/cancel obligation evaporates."""
        return (
            isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is True
            and any(
                isinstance(t, ast.Attribute)
                and t.attr == "daemon"
                and self.res.is_expr(t.value)
                for t in stmt.targets
            )
        )

    # -- the walk ----------------------------------------------------------
    def run(self) -> None:
        self.owned = self._block_discharges(list(self.fn.body))
        self._walk(
            list(self.fn.body), {"unborn"}, protected=False, sinks=(),
            fin=False,
        )

    def _note_exit(self, states: set) -> None:
        if "live" in states:
            self.normal_leak = True

    def _walk(
        self, stmts: list, states: set, protected: bool, sinks: tuple,
        fin: bool,
    ) -> set:
        states = set(states)
        for stmt in stmts:
            if stmt is self.assign:
                states = (states - {"unborn"}) | {"live"}
                continue
            if "live" not in states:
                # before creation / after discharge on all paths: the
                # statement cannot leak this resource
                if isinstance(stmt, (ast.Return,)):
                    return set()
                states = self._walk_inner(stmt, states, protected, sinks, fin)
                continue
            # discharge / daemon-mark / escape checks (same-statement wins)
            if self._block_discharges([stmt]) and not isinstance(
                stmt, (ast.Try, ast.If, ast.For, ast.While, ast.With)
            ):
                self.discharges = True
                states = (states - {"live"}) | {"done"}
                continue
            if self._is_daemon_mark(stmt):
                self.discharges = True
                states = (states - {"live"}) | {"done"}
                continue
            if not self.owned and self._stmt_escapes(stmt):
                self.escaped = True
                states = (states - {"live"}) | {"done"}
                continue
            if self._may_raise(stmt) and not isinstance(stmt, (ast.Try,)):
                for sink in sinks:
                    sink |= states
                if not protected and self.exc_leak is None:
                    self.exc_leak = stmt.lineno
            if isinstance(stmt, ast.Return):
                # a discharging finally runs ON return too: leaving
                # through it is a clean exit, not a normal-path leak
                if not fin:
                    self._note_exit(states)
                return set()
            if isinstance(stmt, ast.Raise):
                return set()
            states = self._walk_inner(stmt, states, protected, sinks, fin)
        return states

    def _walk_inner(
        self, stmt: ast.AST, states: set, protected: bool, sinks: tuple,
        fin: bool,
    ) -> set:
        if isinstance(stmt, ast.If):
            mentions = self.res.name_token() and (
                self.res.name_token() in _names_in(stmt.test)
                or (
                    self.res.target
                    and self.res.target[0] == "attr"
                    and any(
                        isinstance(n, ast.Attribute)
                        and n.attr == self.res.name_token()
                        for n in ast.walk(stmt.test)
                    )
                )
            )
            a = self._walk(list(stmt.body), states, protected, sinks, fin)
            b = self._walk(list(stmt.orelse), states, protected, sinks, fin)
            out = a | b
            if mentions and ("done" in a or "done" in b):
                # `if x is not None: x.stop()` — the None branch holds
                # nothing; treat the branch point as discharging
                self.discharges = True
                out = (out - {"live"}) | {"done"}
            return out
        if isinstance(stmt, (ast.For, ast.While)):
            body = self._walk(list(stmt.body), states, protected, sinks, fin)
            orelse = self._walk(
                list(stmt.orelse), states | body, protected, sinks, fin
            )
            return states | body | orelse
        if isinstance(stmt, ast.With):
            return self._walk(list(stmt.body), states, protected, sinks, fin)
        if isinstance(stmt, ast.Try):
            protects_finally = self._block_discharges(stmt.finalbody)
            protects = protects_finally or any(
                self._block_discharges(h.body) for h in stmt.handlers
            )
            #: states observed at may-raise statements inside the body —
            #: what a handler can actually see on entry (entry/exit
            #: states would claim "live" for a creation that is the
            #: body's LAST statement, a false leak)
            raised: set = set()
            body = self._walk(
                list(stmt.body), states,
                protected or protects,
                sinks + (raised,),
                fin or protects_finally,
            )
            handler_entry = raised or (states - {"live"} or {"unborn"})
            hstates: set = set()
            for h in stmt.handlers:
                # a discharging finally runs even when the HANDLER
                # raises (or returns), so it protects handler bodies too
                hstates |= self._walk(
                    list(h.body), handler_entry,
                    protected or protects_finally, sinks,
                    fin or protects_finally,
                )
            orelse = self._walk(
                list(stmt.orelse), body, protected or protects, sinks,
                fin or protects_finally,
            )
            merged = orelse | hstates if stmt.handlers else orelse
            if stmt.finalbody:
                if self._block_discharges(stmt.finalbody):
                    self.discharges = True
                    merged = (merged - {"live"}) | {"done"}
                else:
                    merged = self._walk(
                        list(stmt.finalbody), merged, protected, sinks, fin
                    )
            return merged
        return states
