"""``lt_ref`` — the CPU oracle: normative LandTrendr semantics in NumPy f64.

This module is the *behavioural specification* of the framework.  The
reference repo (vicchu/land_trendr) implements the LandTrendr temporal
segmentation pipeline inside a class named ``PixelSegmenter`` driven by a
``LandTrendrMapper`` (SURVEY.md §2, provenance ``[B]``); its mount was empty
during the survey (SURVEY.md §0), so this oracle — a faithful scalar
implementation of the published algorithm (Kennedy, Yang & Cohen 2010,
RSE 114(12):2897-2910; SURVEY.md §3.1) — substitutes as the
vertex-for-vertex parity target for the TPU kernel
(``land_trendr_tpu.ops.segment.jax_segment_pixels``).

Every semantic decision the published description leaves open is pinned down
here, explicitly and testably (SURVEY.md §7 build-plan step 1):

* **Tie-breaking** — all argmax/argmin scans break ties toward the smallest
  index.
* **Sign convention** — the segmenter is sign-agnostic.  Index math upstream
  (``land_trendr_tpu.ops.indices``) flips indices so *disturbance is an
  increase* (classic LandTrendr flips e.g. NBR × −1); under that convention
  a *recovery* segment is one whose fitted value decreases.
* **Despike** (Stage 1) — spike proportion for an interior valid point *i*
  with nearest valid neighbours *p*, *q*::

      interp   = y_p + (y_q - y_p) * (t_i - t_p) / (t_q - t_p)
      dev      = |y_i - interp|
      crossing = |y_q - y_p|
      prop_i   = 0 if dev == 0 else max(0, 1 - crossing / dev)

  ``prop == 1`` is a perfect symmetric spike; ``prop == 0`` is no spike.
  Iteratively dampen the *largest* spike (ties → smallest index) by moving
  it toward the interpolation proportionally to its severity
  (``y_i += (interp - y_i) * prop_i``) while ``prop > spike_threshold``;
  ``spike_threshold == 1.0`` therefore disables dampening.  At most
  ``n_valid`` iterations (each dampening strictly reduces that point's
  proportion, so this converges).
* **Vertex search** (Stage 2) — start from the two valid endpoints; grow to
  ``min(max_segments + 1 + vertex_count_overshoot, n_valid)`` vertices by
  repeatedly inserting the interior point with the maximum absolute
  deviation from its segment's OLS line (deviation computed per segment
  over the *closed* point range [v_a, v_b]; global argmax across segments,
  ties → smallest index; points that are already vertices are excluded).
  Insertion happens regardless of deviation magnitude (a zero-deviation
  insertion is harmless — later pruning removes it) so the loop has a fixed
  trip count.  Then cull back to ``min(max_segments + 1, n_candidates)``
  vertices by repeatedly dropping the interior vertex with the smallest
  *angle change*, computed on axis-scaled data: x and y each scaled to
  [0, 1] over the valid range (zero y-range → flat), chord slopes between
  consecutive vertices, ``angle_j = |atan(s_right) - atan(s_left)|``,
  ties → smallest index.
* **Anchored fit** (Stage 3) — segment 1 is an OLS fit over its closed
  point range; each later segment is a slope-only regression through the
  previous segment's fitted endpoint (anchor), over the half-open range
  (v_a, v_b].  Recovery constraints clamp the slope: with R = despiked
  valid range, a slope below ``-recovery_threshold * R`` per year is
  clamped to that limit, and if ``prevent_one_year_recovery`` a negative
  slope on a segment of duration ≤ 1 year is clamped to 0.  The first
  segment's slope is clamped the same way (its intercept is then re-fit as
  ``mean(y) - slope * mean(t)``).  A *point-to-point* fallback trajectory
  (observed despiked values at the vertices, linear in between) replaces
  the regression trajectory iff it violates no recovery constraint and has
  strictly smaller SSE.
* **Model pruning + F-stat selection** (Stage 4) — from the full vertex
  set, iteratively remove the weakest interior vertex (smallest angle
  change, same metric as the cull, ties → smallest index) and refit, down
  to one segment.  Each model with m segments is scored with
  ``df1 = 2m - 1`` and ``df2 = n_valid - 2m`` (each segment contributes a
  slope plus a chosen knot: 2m parameters total including intercept and
  interior knots)::

      F = ((SS0 - SSE) / df1) / (SSE / df2)
      p = F_sf(F; df1, df2)          # survival function

  Models with ``df2 < 1`` or ``SSE > SS0`` (worse than the mean) are
  invalid (p = 1).  Selection: with ``p_best`` the minimum p over valid
  models, choose the model with the *most* segments satisfying
  ``p <= p_best / best_model_proportion``; if the chosen model's p exceeds
  ``p_val_threshold`` return the flat mean model flagged no-fit.
* **Insufficient data** — fewer than ``min_observations_needed`` valid
  years → flat mean model (mean of the valid years, or 0 if none),
  flagged no-fit, with no vertices.

Outputs are fixed-size padded arrays (capacity ``max_segments + 1``
vertices / ``max_segments`` segments) so the vmapped TPU kernel can emit
the identical structure with static shapes (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from land_trendr_tpu.config import LTParams

__all__ = [
    "SegmentationResult",
    "PixelSegmenter",
    "segment_series",
    "despike",
    "find_candidate_vertices",
    "cull_by_angle",
    "fit_model",
    "f_stat_p_value",
    "fit_to_vertices",
]


# ---------------------------------------------------------------------------
# Result container
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SegmentationResult:
    """Fixed-capacity per-pixel segmentation output (SURVEY.md §3.1 outputs).

    Arrays are padded to capacity ``NV = max_segments + 1`` vertices and
    ``NS = max_segments`` segments; ``n_vertices`` gives the live count and
    padded slots hold zeros.  ``vertex_years`` holds *year values* (not
    indices); ``vertex_indices`` holds positions into the input year axis.
    """

    n_vertices: int                 # 0 when no-fit
    vertex_indices: np.ndarray      # (NV,) int32, padded with -1
    vertex_years: np.ndarray        # (NV,) float64
    vertex_src_vals: np.ndarray     # (NV,) float64 — despiked observed values
    vertex_fit_vals: np.ndarray     # (NV,) float64 — fitted trajectory values
    seg_magnitude: np.ndarray       # (NS,) float64 — fit end − fit start
    seg_duration: np.ndarray        # (NS,) float64 — years
    seg_rate: np.ndarray            # (NS,) float64 — magnitude / duration
    rmse: float
    p_of_f: float
    model_valid: bool               # False → no-fit flat model
    fitted: np.ndarray              # (NY,) float64 — fitted value each year
    despiked: np.ndarray            # (NY,) float64 — despiked series (valid yrs)


# ---------------------------------------------------------------------------
# Stage 1 — despike
# ---------------------------------------------------------------------------


def _spike_props(t: np.ndarray, y: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Spike proportion and neighbour interpolation for each interior point.

    ``t``/``y`` are the compacted valid series (no mask).  Endpoints get
    proportion 0.  Returns ``(prop, interp)`` arrays of len(y).
    """
    n = len(y)
    prop = np.zeros(n)
    interp = y.astype(np.float64).copy()
    for i in range(1, n - 1):
        tp, tq = t[i - 1], t[i + 1]
        yp, yq = y[i - 1], y[i + 1]
        itp = yp + (yq - yp) * (t[i] - tp) / (tq - tp)
        dev = abs(y[i] - itp)
        crossing = abs(yq - yp)
        interp[i] = itp
        if dev > 0.0:
            prop[i] = max(0.0, 1.0 - crossing / dev)
    return prop, interp


def despike(t: np.ndarray, y: np.ndarray, spike_threshold: float) -> np.ndarray:
    """Iteratively dampen spikes (Stage 1 spec in the module docstring)."""
    y = y.astype(np.float64).copy()
    n = len(y)
    if n < 3 or spike_threshold >= 1.0:
        return y
    for _ in range(n):
        prop, interp = _spike_props(t, y)
        i = int(np.argmax(prop))        # ties → smallest index
        if prop[i] <= spike_threshold:
            break
        y[i] += (interp[i] - y[i]) * prop[i]
    return y


# ---------------------------------------------------------------------------
# Stage 2 — candidate vertex search + angle cull
# ---------------------------------------------------------------------------


def _ols(t: np.ndarray, y: np.ndarray) -> tuple[float, float]:
    """Closed-form OLS ``y ≈ intercept + slope * t`` (n >= 1; flat if degenerate)."""
    n = len(y)
    tm, ym = float(np.mean(t)), float(np.mean(y))
    stt = float(np.sum((t - tm) ** 2))
    if n < 2 or stt == 0.0:
        return ym, 0.0
    slope = float(np.sum((t - tm) * (y - ym))) / stt
    return ym - slope * tm, slope


def find_candidate_vertices(t: np.ndarray, y: np.ndarray, n_target: int) -> list[int]:
    """Grow vertex set to ``n_target`` by max-deviation insertion (Stage 2)."""
    n = len(y)
    verts = [0, n - 1]
    n_target = min(n_target, n)
    while len(verts) < n_target:
        best_i, best_dev = -1, -1.0
        vs = sorted(verts)
        for a, b in zip(vs[:-1], vs[1:]):
            if b - a < 2:
                continue
            seg_t, seg_y = t[a : b + 1], y[a : b + 1]
            c0, c1 = _ols(seg_t, seg_y)
            for i in range(a + 1, b):
                dev = abs(y[i] - (c0 + c1 * t[i]))
                if dev > best_dev:
                    best_dev, best_i = dev, i
        if best_i < 0:
            break  # no interior points anywhere
        verts.append(best_i)
    return sorted(verts)


def _vertex_angles(t: np.ndarray, y: np.ndarray, verts: list[int]) -> np.ndarray:
    """Angle change at each interior vertex, on axis-scaled data (Stage 2)."""
    t_lo, t_hi = float(t[0]), float(t[-1])
    y_lo, y_hi = float(np.min(y)), float(np.max(y))
    t_rng = t_hi - t_lo if t_hi > t_lo else 1.0
    y_rng = y_hi - y_lo if y_hi > y_lo else 1.0
    xs = [(t[v] - t_lo) / t_rng for v in verts]
    ys = [(y[v] - y_lo) / y_rng for v in verts]
    angles = np.zeros(len(verts))
    for j in range(1, len(verts) - 1):
        s1 = (ys[j] - ys[j - 1]) / (xs[j] - xs[j - 1])
        s2 = (ys[j + 1] - ys[j]) / (xs[j + 1] - xs[j])
        angles[j] = abs(math.atan(s2) - math.atan(s1))
    return angles


def cull_by_angle(
    t: np.ndarray, y: np.ndarray, verts: list[int], n_keep: int
) -> list[int]:
    """Drop min-angle interior vertices until ``n_keep`` remain (Stage 2).

    Known sensitivity of the spec'd angle metric (SURVEY.md §3.1: "slope
    change across the vertex, computed on axis-scaled data"): with x
    scaled by the full time span, one year is dx ≈ 1/NY, so even small
    per-year noise produces near-vertical scaled slopes whose arctans
    saturate toward ±π/2 — a noise wiggle's angle can then rival a real
    disturbance corner's.  Measured (round 4, 100 random noise seeds,
    0.01σ noise on a 0.45-magnitude step + slow recovery): 3/100 pixels
    lose the disturbance vertex to noise vertices at this stage and fall
    back to the 1-segment model.  This is a property of the published
    algorithm's angle formulation, reproduced faithfully here — not a
    kernel defect (the JAX/Pallas kernels match this oracle bit-for-bit);
    lower ``vertex_count_overshoot`` or stronger despike reduce the
    exposure.
    """
    verts = sorted(verts)
    n_keep = max(n_keep, 2)
    while len(verts) > n_keep:
        angles = _vertex_angles(t, y, verts)
        j = 1 + int(np.argmin(angles[1:-1]))  # interior only; ties → smallest
        verts.pop(j)
    return verts


# ---------------------------------------------------------------------------
# Stage 3 — anchored piecewise-linear fit with recovery constraints
# ---------------------------------------------------------------------------


def _clamp_slope(
    slope: float, duration: float, y_range: float, params: LTParams
) -> float:
    """Apply the recovery-rate constraints to a candidate segment slope.

    Disturbance-positive convention: recovery ⇔ negative slope.
    """
    if slope >= 0.0 or y_range <= 0.0:
        return slope
    if params.prevent_one_year_recovery and duration <= 1.0:
        return 0.0
    limit = -params.recovery_threshold * y_range
    return max(slope, limit)


def _segment_violates(
    dy: float, duration: float, y_range: float, params: LTParams
) -> bool:
    """True if a segment's total change violates the recovery constraints."""
    if dy >= 0.0 or y_range <= 0.0 or duration <= 0.0:
        return False
    if params.prevent_one_year_recovery and duration <= 1.0:
        return True
    return (-dy) / duration > params.recovery_threshold * y_range + 1e-12


def fit_model(
    t: np.ndarray,
    y: np.ndarray,
    verts: list[int],
    params: LTParams,
    y_range: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Anchored piecewise-linear LSQ fit through ``verts`` (Stage 3).

    Returns ``(fitted, vertex_fit_vals)`` where ``fitted`` has one value per
    (valid) year and ``vertex_fit_vals`` one per vertex.  Chooses the
    point-to-point fallback when it is constraint-clean and strictly better
    (module docstring).
    """
    n = len(y)
    verts = sorted(verts)
    lo, hi = verts[0], verts[-1]
    fitted = np.zeros(n)

    # --- anchored regression trajectory ---
    a, b = verts[0], verts[1]
    seg_t, seg_y = t[a : b + 1], y[a : b + 1]
    c0, c1 = _ols(seg_t, seg_y)
    c1c = _clamp_slope(c1, float(t[b] - t[a]), y_range, params)
    if c1c != c1:
        c0 = float(np.mean(seg_y)) - c1c * float(np.mean(seg_t))
        c1 = c1c
    fitted[a : b + 1] = c0 + c1 * seg_t
    anchor_t, anchor_y = float(t[b]), float(fitted[b])
    for a, b in zip(verts[1:-1], verts[2:]):
        seg_t, seg_y = t[a + 1 : b + 1], y[a + 1 : b + 1]
        dt = seg_t - anchor_t
        denom = float(np.sum(dt * dt))
        slope = float(np.sum(dt * (seg_y - anchor_y))) / denom if denom > 0 else 0.0
        slope = _clamp_slope(slope, float(t[b] - anchor_t), y_range, params)
        fitted[a + 1 : b + 1] = anchor_y + slope * dt
        anchor_t, anchor_y = float(t[b]), float(fitted[b])

    # --- point-to-point fallback ---
    # SSE comparisons use only the vertex span [lo, hi]; outside the span the
    # trajectory is extended flat (matches np.interp's edge behaviour).
    p2p = np.zeros(n)
    p2p_ok = True
    for a, b in zip(verts[:-1], verts[1:]):
        dur = float(t[b] - t[a])
        dy = float(y[b] - y[a])
        if _segment_violates(dy, dur, y_range, params):
            p2p_ok = False
            break
        seg_t = t[a : b + 1]
        p2p[a : b + 1] = y[a] + (dy / dur if dur > 0 else 0.0) * (seg_t - t[a])
    if p2p_ok:
        sse_reg = float(np.sum((y[lo : hi + 1] - fitted[lo : hi + 1]) ** 2))
        sse_p2p = float(np.sum((y[lo : hi + 1] - p2p[lo : hi + 1]) ** 2))
        if sse_p2p < sse_reg:
            fitted = p2p

    fitted[:lo] = fitted[lo]
    fitted[hi + 1 :] = fitted[hi]
    return fitted, fitted[np.asarray(verts, dtype=int)].copy()


# ---------------------------------------------------------------------------
# Stage 4 — F-statistic scoring, model pruning, selection
# ---------------------------------------------------------------------------


def f_stat_p_value(ss0: float, sse: float, n: int, n_segments: int) -> float:
    """p-of-F for a model (Stage 4 dof spec: df1 = 2m−1, df2 = n−2m)."""
    m = n_segments
    df1, df2 = 2 * m - 1, n - 2 * m
    if df2 < 1 or ss0 <= 0.0 or sse >= ss0:
        return 1.0
    if sse <= 0.0:
        return 0.0
    f = ((ss0 - sse) / df1) / (sse / df2)
    # survival function of F(df1, df2) via the regularised incomplete beta
    from scipy.special import betainc

    x = df2 / (df2 + df1 * f)
    return float(betainc(df2 / 2.0, df1 / 2.0, x))


# ---------------------------------------------------------------------------
# Top-level per-pixel pipeline
# ---------------------------------------------------------------------------


def _flat_result(
    params: LTParams,
    years: np.ndarray,
    values: np.ndarray,
    mask: np.ndarray,
    despiked_valid: np.ndarray | None = None,
) -> SegmentationResult:
    """No-fit flat model: mean of valid years (0 if none), no vertices.

    When despiking already ran, ``despiked_valid`` (the compacted despiked
    valid series) supplies the mean/rmse/despiked outputs so the flat model
    is consistent with the series the pipeline actually scored.
    """
    nv, ns, ny = params.max_vertices, params.max_segments, len(years)
    y_valid = despiked_valid if despiked_valid is not None else values[mask]
    mean = float(np.mean(y_valid)) if mask.any() else 0.0
    despiked_full = values.astype(np.float64).copy()
    despiked_full[~mask] = mean
    if despiked_valid is not None:
        despiked_full[mask] = despiked_valid
    return SegmentationResult(
        n_vertices=0,
        vertex_indices=np.full(nv, -1, dtype=np.int32),
        vertex_years=np.zeros(nv),
        vertex_src_vals=np.zeros(nv),
        vertex_fit_vals=np.zeros(nv),
        seg_magnitude=np.zeros(ns),
        seg_duration=np.zeros(ns),
        seg_rate=np.zeros(ns),
        rmse=float(np.sqrt(np.mean((y_valid - mean) ** 2))) if mask.any() else 0.0,
        p_of_f=1.0,
        model_valid=False,
        fitted=np.full(ny, mean),
        despiked=despiked_full,
    )


def segment_series(
    years: np.ndarray,
    values: np.ndarray,
    mask: np.ndarray,
    params: LTParams,
) -> SegmentationResult:
    """Run the full LandTrendr pipeline on one pixel's annual series.

    Parameters
    ----------
    years : (NY,) year values (monotonically increasing).
    values : (NY,) spectral-index values, disturbance-positive convention.
    mask : (NY,) bool — True where the observation is valid.
    params : algorithm parameters (static).
    """
    years = np.asarray(years, dtype=np.float64)
    values = np.asarray(values, dtype=np.float64)
    # non-finite observations are invalid regardless of the caller's mask
    # (the TPU kernel applies the identical guard)
    mask = np.asarray(mask, dtype=bool) & np.isfinite(values)
    ny = len(years)
    valid_idx = np.flatnonzero(mask)
    n = len(valid_idx)
    if n < params.min_observations_needed:
        return _flat_result(params, years, values, mask)

    t = years[valid_idx]
    y_raw = values[valid_idx]

    # Stage 1
    y = despike(t, y_raw, params.spike_threshold)
    y_range = float(np.max(y) - np.min(y))
    if y_range <= 0.0:
        # constant series: no structure to segment — no-change model
        return _flat_result(params, years, values, mask, despiked_valid=y)

    # Stage 2
    cand = find_candidate_vertices(t, y, params.max_candidates)
    verts_full = cull_by_angle(t, y, cand, min(params.max_vertices, len(cand)))

    # Stage 4 model family: prune weakest interior vertex, refit each time
    ss0 = float(np.sum((y - np.mean(y)) ** 2))
    models: list[tuple[list[int], np.ndarray, np.ndarray, float]] = []
    verts = list(verts_full)
    while True:
        fitted, vfit = fit_model(t, y, verts, params, y_range)
        sse = float(np.sum((y - fitted) ** 2))
        p = f_stat_p_value(ss0, sse, n, len(verts) - 1)
        models.append((list(verts), fitted, vfit, p))
        if len(verts) <= 2:
            break
        angles = _vertex_angles(t, y, verts)
        j = 1 + int(np.argmin(angles[1:-1]))
        verts.pop(j)

    # Selection
    p_best = min(p for *_x, p in models)
    chosen = None
    for verts_m, fitted_m, vfit_m, p_m in models:  # models ordered most→fewest segs
        if p_m <= p_best / params.best_model_proportion:
            chosen = (verts_m, fitted_m, vfit_m, p_m)
            break
    assert chosen is not None
    verts_c, fitted_c, vfit_c, p_c = chosen
    if p_c > params.p_val_threshold:
        return _flat_result(params, years, values, mask, despiked_valid=y)

    # Assemble fixed-capacity outputs
    nv_cap, ns_cap = params.max_vertices, params.max_segments
    k = len(verts_c)
    vertex_indices = np.full(nv_cap, -1, dtype=np.int32)
    vertex_years = np.zeros(nv_cap)
    vertex_src = np.zeros(nv_cap)
    vertex_fit = np.zeros(nv_cap)
    vertex_indices[:k] = valid_idx[verts_c]
    vertex_years[:k] = t[verts_c]
    vertex_src[:k] = y[verts_c]
    vertex_fit[:k] = vfit_c

    seg_mag = np.zeros(ns_cap)
    seg_dur = np.zeros(ns_cap)
    seg_rate = np.zeros(ns_cap)
    for s in range(k - 1):
        seg_mag[s] = vfit_c[s + 1] - vfit_c[s]
        seg_dur[s] = t[verts_c[s + 1]] - t[verts_c[s]]
        seg_rate[s] = seg_mag[s] / seg_dur[s] if seg_dur[s] > 0 else 0.0

    # Year-axis fitted values: interpolate the fitted trajectory across all
    # years (masked years get the trajectory value; outside the vertex span
    # the trajectory is extended flat).
    fitted_full = np.interp(years, t[verts_c], vfit_c)
    sse = float(np.sum((y - fitted_c) ** 2))
    despiked_full = values.astype(np.float64).copy()
    despiked_full[valid_idx] = y

    return SegmentationResult(
        n_vertices=k,
        vertex_indices=vertex_indices,
        vertex_years=vertex_years,
        vertex_src_vals=vertex_src,
        vertex_fit_vals=vertex_fit,
        seg_magnitude=seg_mag,
        seg_duration=seg_dur,
        seg_rate=seg_rate,
        rmse=float(np.sqrt(sse / n)),
        p_of_f=p_c,
        model_valid=True,
        fitted=fitted_full,
        despiked=despiked_full,
    )


def fit_to_vertices(
    years: np.ndarray,
    values: np.ndarray,
    mask: np.ndarray,
    vertex_indices: np.ndarray,
    n_vertices: int,
    params: LTParams,
) -> np.ndarray:
    """FTV: fit *another* index's series to an already-chosen vertex set.

    Classic LandTrendr "fitted trajectory values" (SURVEY.md §3.1 outputs):
    the vertex years come from the segmentation index; the target series is
    anchored-fit through those years.  Returns the full-year fitted series.
    """
    years = np.asarray(years, dtype=np.float64)
    values = np.asarray(values, dtype=np.float64)
    mask = np.asarray(mask, dtype=bool) & np.isfinite(values)
    valid_idx = np.flatnonzero(mask)
    if n_vertices < 2 or len(valid_idx) < 2:
        mean = float(np.mean(values[mask])) if mask.any() else 0.0
        return np.full(len(years), mean)
    t = years[valid_idx]
    y = values[valid_idx]
    # map stack-axis vertex indices → positions in the valid subsequence
    pos = np.searchsorted(valid_idx, vertex_indices[:n_vertices])
    pos = np.clip(pos, 0, len(valid_idx) - 1)
    verts = sorted(set(int(p) for p in pos))
    if len(verts) < 2:
        verts = [0, len(valid_idx) - 1]
    y_range = float(np.max(y) - np.min(y))
    fitted, vfit = fit_model(t, y, verts, params, y_range)
    return np.interp(years, t[verts], vfit)


class PixelSegmenter:
    """Seam-compatible facade over :func:`segment_series`.

    Mirrors the reference's ``PixelSegmenter`` class boundary (SURVEY.md §2,
    the ``LandTrendrMapper``/``PixelSegmenter`` plugin seam, provenance
    ``[B]``): construct with parameters, call :meth:`segment` per series.
    The TPU execution path replaces this with the batched
    ``jax_segment_pixels`` operator at the same seam.
    """

    def __init__(self, params: LTParams | None = None):
        self.params = params or LTParams()

    def segment(
        self,
        years: np.ndarray,
        values: np.ndarray,
        mask: np.ndarray | None = None,
    ) -> SegmentationResult:
        if mask is None:
            mask = np.isfinite(np.asarray(values, dtype=np.float64))
        return segment_series(years, values, mask, self.params)
