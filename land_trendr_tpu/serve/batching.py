"""Cross-job continuous batching: one shared launch, per-job artifacts.

At fleet scale the dominant workload is a flood of small-AOI requests,
and one job = one run = one pipeline: every tiny job pays its own
dispatch, padding, and pipeline-drain overhead while the device idles
between jobs.  This module closes that per-launch waste (ROADMAP item
1's raw-speed story at high QPS) by coalescing the tiles of MANY queued
**same-affinity** jobs behind ONE warm pipeline launch:

* :meth:`JobRequest.affinity_key` already proves shape-compatibility
  without executing — two requests with the same key run the SAME
  compiled programs over the SAME decoded blocks, differing only in
  identity (tenant, priority, deadline, directories, trace id).  The
  ``ProgramCache`` key pins the one compiled program they share.
* The dispatcher therefore runs the **leader** job's Run exactly once
  and, as each tile becomes durable (the driver's ``on_tile_durable``
  hook, AFTER ``manifest.record``), **demuxes** the same arrays into
  every member job's own manifest — same fingerprint, same execution
  context, same deterministic ``.npz`` writer — so every member's
  artifacts are **byte-identical** to a one-run-per-job execution.
* Members are never claimed out of the queue: they drain through the
  normal priority/DRR order and their Runs simply *resume* over the
  demuxed manifests (tiles already done, near-zero device work), so
  first-write-wins durability, resume, quarantine, cancel and SLO
  semantics are the stock per-job semantics, untouched.  Batching
  changes packing, never fairness ordering.

Failure isolation is structural: a ``batch.pack`` fault excludes one
candidate (it runs solo later); a ``batch.demux`` fault — or a member
cancelled mid-batch — stops THAT member's demux only, and its own run
recomputes whatever is missing, byte-identically.  A leader dying
mid-batch leaves every member a partially-demuxed manifest its normal
resume completes.  A SIGKILL mid-batch is just the crash story the
manifest already tells.

The **shared batch buffer** is pre-touched per launch through a jitted
donated program (SNIPPETS.md [2]'s ``donate_argnames`` dispatch-path
pattern, mirroring ``runtime/feed.unpack_inputs``): the batch-shaped
scratch allocation is consumed and its handle dropped before the run's
real uploads start, so the allocator serves the launch from warm pages
instead of growing under the first tile.
"""

from __future__ import annotations

import functools
import logging
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.runtime import faults
from land_trendr_tpu.runtime.manifest import TileManifest

__all__ = ["CrossJobBatch", "resolve_batch", "warm_batch_buffer"]

log = logging.getLogger("land_trendr_tpu.serve.batching")

# _consume_batch_buffer donates its scratch buffer (see its docstring);
# on backends where donation is unusable (CPU shares host memory) JAX
# warns once per compile.  Expected and not actionable wherever this
# module is used, so the one message-targeted filter installs at import
# — NOT per call (the filter list is process-global).
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


@functools.partial(jax.jit, donate_argnames=("buf",))
def _consume_batch_buffer(buf: jnp.ndarray) -> jnp.ndarray:
    """Touch every page of the shared batch scratch buffer and hand it
    back to the allocator.

    The buffer is **donated** (SNIPPETS.md [2]'s ``donate_argnames``
    dispatch-path pattern, the twin of ``runtime/feed.unpack_inputs``):
    it is dead the moment this reduction reads it — the launch keeps no
    reference, and the caller drops its handle right after consuming
    the result — so XLA may alias the pages into the scalar output and
    the allocator re-serves them to the run's real per-tile uploads
    instead of holding a second batch-sized allocation live.  On
    backends where donation is unusable (CPU) JAX just keeps the copy —
    behavior, not bytes, is what the hint changes.
    """
    return jnp.sum(buf, dtype=jnp.float32)


def warm_batch_buffer(n_px: int, n_years: int) -> float:
    """Pre-touch the batch-shaped scratch allocation for one shared
    launch: allocate the padded ``(n_px, n_years)`` buffer the batch's
    tiles will stream through, run the donated consume program, and
    drop the handle.  Best-effort — a warmup failure must never fail
    the batch (the launch just pays first-tile allocation instead)."""
    try:
        buf = jnp.zeros((int(n_px), int(n_years)), dtype=jnp.float32)
        out = float(_consume_batch_buffer(buf))
        del buf  # the donated handle is dead; drop it before the launch
        return out
    except Exception:
        log.debug("batch buffer warmup failed", exc_info=True)
        return 0.0


def resolve_batch(
    batch: "bool | str",
    tune_store_dir: "str | None" = None,
    scene_shape: "tuple[int, int, int] | None" = None,
) -> bool:
    """Resolve the ``ServeConfig.batch`` knob ("auto"/True/False) to a bool.

    Explicit values ALWAYS win (the autotuner contract).  ``"auto"``
    consults the replica's tuning store (the PR-14
    :class:`~land_trendr_tpu.tune.store.TuningStore`) for this device's
    profile over the scene's shape class: a profile carrying a
    ``"batch"`` knob pins the verdict; no store, no profile, or no such
    knob defaults **ON** — batching is byte-identical packing with no
    numeric trade, so only a measured regression (the window wait
    dominating tiny scenes) should ever turn it off.
    """
    if batch is True or batch is False:
        return batch
    if batch != "auto":
        raise ValueError(
            f"batch={batch!r} must be True, False or 'auto'"
        )
    if tune_store_dir and scene_shape is not None:
        from land_trendr_tpu.tune.autotune import device_identity
        from land_trendr_tpu.tune.store import TuningStore, shape_class

        try:
            device_kind, backend = device_identity()
            profile = TuningStore(tune_store_dir).load(
                device_kind, backend, shape_class(*scene_shape)
            )
        except Exception:
            log.debug("batch tuning-store resolution failed", exc_info=True)
            profile = None
        if profile is not None:
            return bool(profile.get("knobs", {}).get("batch", True))
    return True


class _Member:
    """One batch member's demux state: its lazily-opened manifest, the
    demuxed-tile ledger, and the active flag a fault/cancel clears."""

    def __init__(self, job) -> None:
        self.job = job
        self.manifest: "TileManifest | None" = None
        self.done: "set[int]" = set()
        self.tiles = 0
        self.active = True
        self.error: "str | None" = None


class CrossJobBatch:
    """One coalesced launch: a leader Run plus the member jobs its
    durable tiles demux into.

    Lifecycle (driven by the server's dispatcher):

    1. construct with the popped leader job and the same-affinity
       members collected from the contiguous front of the queue;
    2. :meth:`open` once the leader's Run exists (knobs resolved, tiles
       planned) — trims members to ``batch_max_tiles``, stamps the
       run's ``batch_*`` progress keys, warms the shared buffer, and
       returns the ``batch_launch`` stats;
    3. the Run's ``on_tile_durable`` hook calls :meth:`demux_tile` per
       durable tile (writer threads — internally locked);
    4. :meth:`finalize` after the leader's execute returns the
       per-member ``batch_demux`` stats.

    Never raises into the driver: a demux failure deactivates that one
    member (its own run recomputes, byte-identically) and batch-mates
    proceed.
    """

    def __init__(self, leader, members, *, compress: str = "none") -> None:
        self.leader = leader
        self.members = [_Member(j) for j in members]
        self.compress = compress
        self.run = None
        self._lock = threading.Lock()
        self._stats: "dict | None" = None

    @property
    def jobs(self) -> int:
        """Jobs sharing the launch (leader + still-packed members)."""
        return 1 + len(self.members)

    def open(self, run, *, max_tiles: int = 0, window_wait_s: float = 0.0) -> dict:
        """Bind the leader's constructed Run and settle the batch shape.

        ``max_tiles`` (``ServeConfig.batch_max_tiles``) bounds the
        TOTAL coalesced tiles — jobs × tiles-per-job; members past the
        bound are dropped here and simply run solo in their normal
        queue turn.  Returns the ``batch_launch`` event stats.
        """
        self.run = run
        # demuxed artifacts must be the bytes the member's own run
        # would have written — same compression knob included
        self.compress = run.cfg.manifest_compress
        per_job = max(1, len(run.tiles))
        if max_tiles:
            keep = max(0, max_tiles // per_job - 1)
            if keep < len(self.members):
                dropped = self.members[keep:]
                self.members = self.members[:keep]
                log.info(
                    "batch bounded at %d tiles: %d member(s) run solo",
                    max_tiles, len(dropped),
                )
        ts = int(run.cfg.tile_size)
        useful_px = sum(t.h * t.w for t in run.tiles)
        padded_per_job = per_job * ts * ts
        n_jobs = self.jobs
        occupancy = (
            useful_px / padded_per_job if padded_per_job else 1.0
        )
        run.progress.update(
            batch_jobs=n_jobs,
            batch_tiles=n_jobs * per_job,
            batch_occupancy=round(occupancy, 4),
        )
        if self.members:
            warm_batch_buffer(ts * ts, run.stack.n_years)
        self._stats = {
            "jobs": n_jobs,
            "tiles": n_jobs * per_job,
            "padded_px": n_jobs * padded_per_job,
            "occupancy": round(min(1.0, max(occupancy, 1e-9)), 6),
            "window_wait_s": round(window_wait_s, 6),
        }
        return dict(self._stats)

    def _member_manifest(self, m: _Member) -> TileManifest:
        """The member's own manifest, opened on first demux with the
        LEADER's fingerprint + execution context (same affinity ⇒ same
        fingerprint; same process ⇒ same context), so the member's own
        resumed Run validates and skips every demuxed tile."""
        if m.manifest is None:
            lead = self.run.manifest
            m.manifest = TileManifest(
                m.job.workdir,
                lead.fingerprint,
                context=(
                    dict(lead.context) if lead.context is not None else None
                ),
            )
            # first-write-wins across batches too: a member demuxed by
            # an earlier batch (or resuming a pinned workdir) keeps its
            # durable tiles — demux never overwrites a done artifact
            m.done = m.manifest.open(resume=True)
        return m.manifest

    def demux_tile(self, t, arrays: dict, meta: dict) -> None:
        """The leader Run's ``on_tile_durable`` hook: fan one durable
        tile out to every still-active member's manifest.

        Runs on the leader's writer threads (locked — member manifests
        append sequentially).  Per-member isolation: a ``batch.demux``
        fault or any real write error deactivates THAT member only —
        its own run recomputes the missing tiles byte-identically —
        and a member cancelled while queued stops receiving tiles."""
        with self._lock:
            for m in self.members:
                if not m.active:
                    continue
                if m.job.cancel.is_set() or m.job.state not in (
                    "queued", "running"
                ):
                    m.active = False
                    continue
                try:
                    faults.check("batch.demux")
                    man = self._member_manifest(m)
                    if t.tile_id in m.done:
                        continue  # first write won already
                    # the leader's meta minus lease attribution: the
                    # arrays (the byte-identity surface) are shared; the
                    # manifest line is informational either way
                    man.record(
                        t.tile_id,
                        arrays,
                        {k: v for k, v in meta.items() if k != "owner"},
                        compress=self.compress,
                    )
                    m.tiles += 1
                except Exception as e:
                    m.active = False
                    m.error = f"{type(e).__name__}: {e}"
                    log.warning(
                        "batch demux to job %s stopped after %d tile(s): "
                        "%s (its own run recomputes the rest)",
                        m.job.job_id, m.tiles, m.error,
                    )

    def finalize(self) -> list:
        """Per-member ``batch_demux`` stats after the leader's execute:
        ``(job, tiles_demuxed, error, complete)`` tuples in pack order.
        ``complete`` means the member's manifest now covers every tile
        the leader planned (pre-existing durable tiles included) — its
        queue turn is a pure resume, so the dispatcher skips the batch
        window for it entirely."""
        n_tiles = len(self.run.tiles) if self.run is not None else 0
        with self._lock:
            return [
                (
                    m.job,
                    m.tiles,
                    m.error,
                    n_tiles > 0 and len(m.done) + m.tiles >= n_tiles,
                )
                for m in self.members
            ]
