"""Segmentation-as-a-service: the long-lived server runtime.

The serve subpackage turns the one-shot ``segment`` pipeline into a
resident service (ROADMAP item 1): warm compiled programs
(:mod:`~land_trendr_tpu.serve.programs`), a bounded job queue with
admission control and per-tenant caps over a loopback HTTP JSON API and
a filesystem drop-box (:mod:`~land_trendr_tpu.serve.server`), and
request-scoped observability (job lifecycle events, ``lt_serve_*``
instruments, job_id threaded through every run event).  CLI entry:
``lt serve`` beside ``segment``.
"""

from land_trendr_tpu.serve.config import ServeConfig
from land_trendr_tpu.serve.jobs import (
    EXIT_CODE_FOR_STATE,
    TERMINAL_STATES,
    Job,
    JobRequest,
)
from land_trendr_tpu.serve.programs import ProgramCache
from land_trendr_tpu.serve.server import Rejection, SegmentationServer

__all__ = [
    "EXIT_CODE_FOR_STATE",
    "TERMINAL_STATES",
    "Job",
    "JobRequest",
    "ProgramCache",
    "Rejection",
    "SegmentationServer",
    "ServeConfig",
]
