"""Job model: the request schema, lifecycle states, and exit-code mapping.

A **job** is one AOI/param segmentation request — the serve-mode unit of
work, exactly what one ``lt segment`` invocation does, minus the process
start, config parse, jit compile and (with the shared ingest store) the
TIFF decode that invocation would pay.  Requests arrive as JSON (HTTP
POST or drop-box file), are validated into :class:`JobRequest`, and run
through a warm :class:`~land_trendr_tpu.runtime.driver.Run`.

Job states map onto the documented CLI exit-code contract (README
§Failure semantics) so orchestrators reason about one table:

=====================  ====  =================================================
state                  exit  meaning
=====================  ====  =================================================
``done``               0     run + assembly completed
``config_error``       2     bad request / bad stack (not retryable as-is)
``retries_exhausted``  3     tile(s) exhausted retries / quarantined —
                             manifest resumable (see below)
``stalled``            4     job timeout (the stall watchdog's job-level
                             analog) — manifest resumable
``cancelled``          3     explicit cancel — manifest resumable like any
                             retryable abort
``error``              1     unclassified failure (server-side defect)
=====================  ====  =================================================

**Resuming**: each fresh submission gets a fresh ``jobs/<id>/work``
manifest, so resuming a retryable job means resubmitting with the OLD
job's ``workdir`` pinned in the request (the terminal error string and
the job's status snapshot both carry it); only then does the new job
complete exactly the remaining tiles.

``queued`` / ``running`` are the non-terminal states; ``rejected``
submissions never become jobs (they are answered at admission with the
429-style response and a ``job_rejected`` event).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time

__all__ = [
    "EXIT_CODE_FOR_STATE",
    "TERMINAL_STATES",
    "Job",
    "JobRequest",
]

#: terminal job states (see the module docstring's mapping table)
TERMINAL_STATES = (
    "done",
    "config_error",
    "retries_exhausted",
    "stalled",
    "cancelled",
    "error",
)

#: job state → the CLI exit code the same outcome would have produced
EXIT_CODE_FOR_STATE = {
    "done": 0,
    "error": 1,
    "config_error": 2,
    "retries_exhausted": 3,
    "cancelled": 3,
    "stalled": 4,
}


@dataclasses.dataclass(frozen=True)
class JobRequest:
    """One validated AOI/param request.

    The fields mirror the ``segment`` CLI surface a client would
    otherwise drive; ``run_overrides`` passes any further
    :class:`~land_trendr_tpu.runtime.driver.RunConfig` field straight
    through (validated by RunConfig itself — an unknown field or bad
    value is a ``config_error``).  Cache/store knobs are NOT accepted:
    the server owns the process-wide cache and the shared ingest store.
    """

    stack_dir: str
    index: str = "nbr"
    ftv: tuple[str, ...] = ()
    params: "dict | None" = None
    tile_size: "int | str" = 256  # "auto" = tuning-store resolution
    products: "tuple[str, ...] | None" = None
    workdir: "str | None" = None  # default <serve workdir>/jobs/<id>/work
    out_dir: "str | None" = None  # default <serve workdir>/jobs/<id>/out
    tenant: str = "default"
    priority: int = 0  # higher drains first; FIFO within a priority
    timeout_s: "float | None" = None  # overrides ServeConfig.job_timeout_s
    #: SLO deadline, seconds, submit→terminal — ACCOUNTING ONLY: a job
    #: past its deadline keeps running to its natural terminal state
    #: (use ``timeout_s`` to enforce a bound); the miss surfaces as
    #: ``deadline_exceeded`` in the status snapshot, ``met=false`` on
    #: the ``job_slo`` event, and the ``lt_slo_*`` instruments
    deadline_s: "float | None" = None
    max_retries: int = 2
    quarantine_tiles: bool = False
    lazy: bool = False  # windowed C2 ingest (the ingest-store workload)
    assemble: bool = True  # mosaic rasters after the run
    #: resume the manifest found in THIS job's workdir — effective for
    #: resubmissions only when the request pins the prior job's
    #: ``workdir`` (fresh submissions get fresh jobs/<id>/work dirs)
    resume: bool = True
    run_overrides: "dict | None" = None
    #: request-tracing correlation id: stamped by the fleet router at
    #: ITS admission and carried through the forward payload, so a
    #: re-routed submission keeps the original id; a direct submission
    #: leaves it None and the server mints one at serve admission.
    #: Deliberately EXCLUDED from the affinity key — two requests that
    #: differ only in identity run the same programs.
    trace_id: "str | None" = None
    #: client-chosen resubmission token: the fleet router remembers it in
    #: the admission journal, so a duplicate submission (a retry after a
    #: timed-out 200, before OR after a router restart) returns the
    #: EXISTING job instead of double-running.  Like ``trace_id``,
    #: identity only — excluded from the affinity key.
    idempotency_key: "str | None" = None

    #: the per-run knobs the server owns (shared cache/store) or that
    #: cannot mean anything inside a server process — rejected even via
    #: run_overrides, so a request cannot clobber sibling jobs
    _RESERVED_OVERRIDES = (
        "feed_cache_mb",
        "decode_workers",
        "ingest_store_mb",
        "ingest_store_dir",
        "telemetry",
        "metrics_port",
        "metrics_host",
        "fault_schedule",
        "stall_timeout_s",
    )

    @classmethod
    def from_payload(cls, payload: dict) -> "JobRequest":
        """Parse + validate one submission payload (HTTP body or
        drop-box file).  Raises ``ValueError`` on anything malformed —
        the admission layer maps that to a 400-class rejection."""
        if not isinstance(payload, dict):
            raise ValueError(
                f"job request must be a JSON object, got "
                f"{type(payload).__name__}"
            )
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(payload) - known)
        if unknown:
            raise ValueError(f"unknown job request field(s): {unknown}")
        if "stack_dir" not in payload or not isinstance(
            payload["stack_dir"], str
        ):
            raise ValueError("job request needs a string 'stack_dir'")
        kw = dict(payload)
        if "ftv" in kw:
            if isinstance(kw["ftv"], str):
                kw["ftv"] = tuple(s for s in kw["ftv"].split(",") if s)
            else:
                kw["ftv"] = tuple(kw["ftv"])
        if kw.get("products") is not None:
            kw["products"] = tuple(kw["products"])
        req = cls(**kw)
        if req.priority < -100 or req.priority > 100:
            raise ValueError(
                f"priority={req.priority} outside -100..100"
            )
        if req.timeout_s is not None and req.timeout_s <= 0:
            raise ValueError(f"timeout_s={req.timeout_s} must be > 0")
        if req.deadline_s is not None and req.deadline_s <= 0:
            raise ValueError(f"deadline_s={req.deadline_s} must be > 0")
        if isinstance(req.tile_size, str):
            # the tuning-store sentinel: resolved at Run construction
            # through the replica's shared store (README §Autotuning)
            if req.tile_size != "auto":
                raise ValueError(
                    f"tile_size={req.tile_size!r} must be an integer or "
                    "'auto'"
                )
        elif req.tile_size < 1:
            raise ValueError(f"tile_size={req.tile_size} must be >= 1")
        if req.max_retries < 0:
            raise ValueError(
                f"max_retries={req.max_retries} must be >= 0"
            )
        if not req.tenant or not isinstance(req.tenant, str):
            raise ValueError("tenant must be a non-empty string")
        if req.trace_id is not None and (
            not isinstance(req.trace_id, str) or not req.trace_id
        ):
            raise ValueError("trace_id must be a non-empty string")
        if req.idempotency_key is not None and (
            not isinstance(req.idempotency_key, str)
            or not req.idempotency_key
        ):
            raise ValueError("idempotency_key must be a non-empty string")
        overrides = req.run_overrides or {}
        if not isinstance(overrides, dict):
            raise ValueError("run_overrides must be a JSON object")
        reserved = sorted(set(overrides) & set(cls._RESERVED_OVERRIDES))
        if reserved:
            raise ValueError(
                f"run_overrides may not set server-owned field(s): "
                f"{reserved}"
            )
        return req

    #: the request fields that select the compiled-program/decoded-block
    #: set a job needs — the AFFINITY key's inputs.  Tenant, priority,
    #: deadlines and directory pins deliberately excluded: two requests
    #: that differ only in those run the SAME programs over the SAME
    #: blocks, so they must hash identically for warm routing.
    _AFFINITY_FIELDS = (
        "stack_dir",
        "index",
        "ftv",
        "params",
        "tile_size",
        "products",
        "lazy",
        "run_overrides",
    )

    def affinity_key(self) -> str:
        """Deterministic warm-affinity key over the shape-relevant
        request fields (see ``_AFFINITY_FIELDS``).

        This is the routing-layer sibling of
        :meth:`~land_trendr_tpu.serve.programs.ProgramCache.key_for`:
        the program-cache key hashes facts only the executing process
        knows (backend, mesh, padded pixel counts), while this key
        hashes the REQUEST alone — so a front-end router and a replica
        compute the same key for the same submission without running
        it.  Repeat shapes hash identically; ``/healthz`` exposes each
        replica's recently-run keys (bounded) for the router's affinity
        table."""
        facts = {name: getattr(self, name) for name in self._AFFINITY_FIELDS}
        blob = json.dumps(facts, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def to_run_config(self, workdir: str, out_dir: str, telemetry: bool):
        """Project this request onto a RunConfig over the job's resolved
        directories.

        The server's cache/store configuration deliberately does NOT
        ride the RunConfig (the Run uses the process-wide cache and the
        server's ``shared_store`` as configured once at startup);
        RunConfig validation errors propagate as ``ValueError`` — the
        ``config_error`` terminal state.
        """
        from land_trendr_tpu.config import LTParams
        from land_trendr_tpu.runtime import RunConfig

        kw = dict(
            index=self.index,
            ftv_indices=tuple(self.ftv),
            params=LTParams.from_dict(self.params or {}),
            tile_size=self.tile_size,
            products=self.products,
            workdir=workdir,
            out_dir=out_dir,
            resume=self.resume,
            max_retries=self.max_retries,
            quarantine_tiles=self.quarantine_tiles,
            telemetry=telemetry,
        )
        kw.update(self.run_overrides or {})
        return RunConfig(**kw)


@dataclasses.dataclass
class Job:
    """One admitted job's mutable server-side record.

    All mutation happens under the server's lock (the dispatcher and the
    HTTP handler threads share these records); :meth:`status` snapshots
    a JSON-safe view for the API.
    """

    job_id: str
    request: JobRequest
    source: str = "http"  # "http" | "dropbox"
    #: the request-tracing correlation id: the request's own (router
    #: forwards carry it) or minted at serve admission for direct jobs
    trace_id: str = ""
    state: str = "queued"
    submitted_t: float = dataclasses.field(default_factory=time.time)
    started_t: "float | None" = None
    finished_t: "float | None" = None
    error: "str | None" = None
    summary: "dict | None" = None
    outputs: "dict | None" = None
    workdir: "str | None" = None
    out_dir: "str | None" = None
    #: the Run-level cancel event (explicit cancel AND job timeout)
    cancel: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    timed_out: bool = False
    #: set by the dispatcher when a shared launch fully demuxed this
    #: job's manifest: its own queue turn is a pure resume, so the
    #: dispatcher holds no batch window for it and never re-packs it
    #: (a batch behind a no-work leader demuxes nothing)
    batch_demuxed: bool = False
    dropbox_path: "str | None" = None
    #: the live Run object while the job executes (the /debug/jobs
    #: progress feed); RELEASED at terminal — a Run pins the job's whole
    #: decoded stack, which a long-lived server must not accumulate
    run: "object | None" = None

    def _latency_split_locked(self) -> "tuple[float, float]":
        """``(queue_wait_s, exec_s)`` with each leg clamped ≥ 0; caller
        holds the server lock.  Latency is DERIVED as their sum, never
        re-measured end−submit: a backwards wall-clock step between the
        three stamps could otherwise break the ``queue_wait + exec <=
        latency`` split the schema value-lint hard-enforces — and this
        one derivation serves both ``slo_locked`` (terminal verdict) and
        ``status_locked`` (live ``deadline_exceeded``) so the two can
        never disagree about the same job."""
        end = self.finished_t if self.finished_t is not None else time.time()
        start = self.started_t if self.started_t is not None else end
        return max(0.0, start - self.submitted_t), max(0.0, end - start)

    def slo_locked(self) -> dict:
        """SLO accounting for a TERMINAL job (caller holds the server
        lock): the latency split — queue wait (submit→dispatch) vs
        execution (dispatch→terminal) — and the deadline verdict.
        A job cancelled while still queued has ``exec_s`` 0 and a queue
        wait spanning its whole life.  The verdict is accounting, never
        enforcement: ``met`` is True when no ``deadline_s`` was set.
        """
        queue_wait, exec_s = self._latency_split_locked()
        latency = queue_wait + exec_s
        deadline = self.request.deadline_s
        out = {
            "queue_wait_s": round(queue_wait, 6),
            "exec_s": round(exec_s, 6),
            "latency_s": round(latency, 6),
            "met": deadline is None or latency <= deadline,
        }
        if deadline is not None:
            out["deadline_s"] = deadline
        return out

    def status_locked(self) -> dict:
        """JSON-safe snapshot; caller holds the server lock."""
        out = {
            "job_id": self.job_id,
            "trace_id": self.trace_id,
            "state": self.state,
            "tenant": self.request.tenant,
            "priority": self.request.priority,
            "source": self.source,
            "submitted_t": self.submitted_t,
            "started_t": self.started_t,
            "finished_t": self.finished_t,
            "workdir": self.workdir,
            "out_dir": self.out_dir,
        }
        if self.state in TERMINAL_STATES:
            out["exit_code"] = EXIT_CODE_FOR_STATE.get(self.state, 1)
        deadline = self.request.deadline_s
        if deadline is not None:
            # live surfacing: a RUNNING job past its deadline already
            # reads deadline_exceeded — the SLO is about the requester's
            # clock, not the job's eventual terminal state
            if sum(self._latency_split_locked()) > deadline:
                out["deadline_exceeded"] = True
        if self.error is not None:
            out["error"] = self.error
        if self.summary is not None:
            out["summary"] = self.summary
        if self.outputs is not None:
            out["outputs"] = self.outputs
        return out
