"""Warm program cache: compiled-program admission across jobs.

Every device program the tile pipeline runs — the fused segmentation
program (:func:`land_trendr_tpu.ops.tile.process_tile_dn`), the packed
fetch/upload pack+unpack programs — is a **module-level** ``jax.jit``
function with static arguments, so XLA executables live in JAX's
in-process jit cache and stay resident for the life of the process.
What a long-lived server needs on top is the *contract* and the
*accounting*:

* an explicit **cache key** over everything that selects a distinct
  executable set — the run fingerprint (index, params, tile/chunk
  geometry, products, years shape), the backend, the resolved kernel
  impl, the mesh width, the packed-path choices, and the fed dtypes —
  so "warm" is a checkable property, not a hope;
* **admission**: the driver's serve path
  (:class:`land_trendr_tpu.runtime.driver.Run` with ``programs=``) asks
  this cache before the first tile.  A **miss** pays the compile right
  there, against one fully-masked dummy tile pushed through the exact
  upload → dispatch → fetch chain (the executables JAX caches are the
  ones every real tile reuses); a **hit** skips the probe entirely — a
  warm job runs **zero** compiles, which ``tools/serve_bench.py``
  measures and the perf gate asserts structurally;
* **observability**: per-run hit/miss/compile_s (the ``program_cache``
  event) and server-wide totals for the ``lt_serve_*`` warm-ratio
  instruments.

The process is the residency boundary: keys index executables that JAX
itself keeps alive, so there is nothing to pin and nothing to evict —
the entry table is bytes per key, not megabytes per program.
"""

from __future__ import annotations

import hashlib
import json
import threading

__all__ = ["ProgramCache"]


class ProgramCache:
    """Thread-safe admission index + accounting over JAX's jit cache."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: key → compile seconds its miss paid
        self._compiled: dict[str, float] = {}
        self._hits = 0
        self._misses = 0
        self._compile_s = 0.0

    @staticmethod
    def key_for(**facts) -> str:
        """Deterministic key over the compile-relevant run facts.

        Callers pass plain JSON-able values (the driver passes the run
        fingerprint, backend, impl, mesh width, padded pixel count,
        years count, chunking, packed-path flags, and fed dtypes); the
        key is the sorted-JSON digest, so fact ordering never matters
        and new facts can ride along without a format change.
        """
        blob = json.dumps(facts, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()

    def admit(self, key: str) -> bool:
        """True when ``key``'s programs are already resident (a warm
        run); False when the caller must compile (and then
        :meth:`record` the miss)."""
        with self._lock:
            return key in self._compiled

    def record(
        self, key: str, hit: bool, compile_s: float = 0.0, ok: bool = True
    ) -> None:
        """Account one run's verdict; a SUCCESSFUL miss registers the
        key as resident for every later run.  ``ok=False`` (the warm
        probe failed — nothing was compiled) counts the miss but leaves
        the key unregistered, so the next same-key run probes again
        instead of being falsely admitted warm."""
        with self._lock:
            if hit:
                self._hits += 1
            else:
                self._misses += 1
                self._compile_s += float(compile_s)
                if ok:
                    self._compiled.setdefault(key, float(compile_s))

    def stats(self) -> dict:
        """Server-wide totals: hits/misses/compile_s plus the resident
        key count (the ``program_cache`` server-scope aggregate and the
        ``lt_serve_*`` warm-ratio feed)."""
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "compile_s": round(self._compile_s, 6),
                "keys": len(self._compiled),
            }
