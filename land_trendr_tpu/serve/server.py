"""Segmentation-as-a-service: the long-lived server runtime.

One :class:`SegmentationServer` process keeps everything a ``segment``
invocation pays for *resident across requests* — the JAX executables
(via the :class:`~land_trendr_tpu.serve.programs.ProgramCache` admission
index), the process-wide decoded-block cache, and the shared persistent
ingest store ("ingest once, serve many") — and drains a bounded job
queue through warm :class:`~land_trendr_tpu.runtime.driver.Run` objects.
A warm job (same request shape, same stacks) runs **zero** jit compiles
and **zero** TIFF decodes; ``tools/serve_bench.py`` measures exactly
that and the perf gate asserts it structurally.

Layout:

* **submission** — a loopback-only HTTP JSON API (stdlib
  ``http.server``; the bind address is validated by
  :class:`~land_trendr_tpu.serve.config.ServeConfig` — the job API is an
  unauthenticated control surface) plus a filesystem drop-box for batch
  use, both funneling through ONE admission path;
* **admission control** — bounded queue depth and a per-tenant in-flight
  cap, each rejected with HTTP 429 (``job_rejected`` event,
  ``lt_serve_rejections_total``) so backlog is the client's problem, not
  the server's memory;
* **scheduling** — a priority queue (higher ``priority`` first, FIFO
  within a priority) drained by ONE dispatcher on the thread that called
  :meth:`SegmentationServer.serve_forever`; tiles inside a job already
  pipeline across feed/upload/compute/fetch/write, so job-level
  parallelism would only thrash the device;
* **failure semantics** — per-job timeout and cancel ride the run's
  cancel event (the manifest stays resumable; a resubmitted job resumes
  it), tile-level faults keep their retry/quarantine contract, and a
  job that exhausts retries is reported failed WITHOUT taking down the
  server or sibling jobs (the ``serve.submit`` / ``serve.job`` fault
  seams soak exactly this).  Job states map onto the CLI exit-code
  contract (:data:`~land_trendr_tpu.serve.jobs.EXIT_CODE_FOR_STATE`).

Observability: the server writes its own ``events.jsonl`` scope (job
lifecycle + admission + the program-cache aggregate) and ``lt_serve_*``
instruments under its workdir; every job's run writes its own scope
under the job workdir with the ``job_id`` threaded onto every event.
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import http.server
import json
import logging
import os
import threading
import time
import uuid
from typing import Any

from land_trendr_tpu.io import blockcache
from land_trendr_tpu.obs.events import EventLog
from land_trendr_tpu.obs.flight import (
    FlightRecorder,
    ResourceSampler,
    flight_path,
)
from land_trendr_tpu.obs.metrics import (
    MetricsHTTPServer,
    MetricsRegistry,
    PromFileExporter,
)
from land_trendr_tpu.runtime import faults
from land_trendr_tpu.serve.config import ServeConfig
from land_trendr_tpu.serve.jobs import Job, JobRequest
from land_trendr_tpu.serve.programs import ProgramCache

__all__ = ["Rejection", "SegmentationServer"]

log = logging.getLogger("land_trendr_tpu.serve")

#: job-latency histogram buckets: sub-second warm smokes through
#: multi-hour scene jobs
_JOB_BUCKETS = (0.5, 1, 2, 5, 10, 30, 60, 300, 1800, 7200, 43200)

#: ``lt_slo_burn_rate`` window, terminal jobs
_SLO_WINDOW_JOBS = 100

#: bound on the request-level warm-affinity keys ``/healthz`` exposes
#: (recency-ordered; the full program residency count stays
#: ``warm_program_count`` — this list only has to cover the shapes a
#: router would still route here)
_WARM_KEYS_MAX = 32


class Rejection(Exception):
    """A submission refused at admission: carries the HTTP status and a
    machine-readable reason (``queue_full`` / ``tenant_cap`` /
    ``bad_request`` / ``submit_error`` / ``shutting_down``)."""

    def __init__(self, http_status: int, reason: str, detail: str = "") -> None:
        super().__init__(detail or reason)
        self.http_status = int(http_status)
        self.reason = reason
        self.detail = detail


class _ServeTelemetry:
    """The server's own events scope + ``lt_serve_*`` instruments.

    Job lifecycle, admission verdicts and the warm-cache aggregate live
    HERE (one scope for the server's whole life); per-job tile traffic
    lives in each job's own run scope under the job workdir.  The stream
    opens with a ``run_start`` (fingerprint ``"serve"``, zero tiles) and
    closes with a ``run_done`` so every existing consumer — schema lint,
    ``obs_report`` — folds it without special cases.
    """

    def __init__(
        self,
        cfg: ServeConfig,
        probes: "Any | None" = None,
        publish_probes: "Any | None" = None,
    ) -> None:
        os.makedirs(cfg.workdir, exist_ok=True)
        # fleet telemetry plane handles: predeclared so _release() is
        # callable from any depth of a partially finished construction
        # (the LT008 lesson the rest of this class already carries)
        self._publisher = None
        self.history = None
        self.engine = None
        self._fleet_thread: "threading.Thread | None" = None
        self._fleet_stop = threading.Event()
        self._fleet_lock = threading.Lock()
        self._active_alerts: list = []
        self._fleet_counts = {"folded": 0, "stale": 0, "corrupt": 0}
        #: the flight ring behind /debug/flight: mirrors every SERVER
        #: event here plus every JOB run's events (the server threads
        #: this recorder into each Run's telemetry), so the ring shows
        #: the process's whole recent story in one window
        self.flight = (
            FlightRecorder(cfg.flight_ring_events)
            if cfg.flight_ring_events
            else None
        )
        self.events = EventLog(
            os.path.join(cfg.workdir, "events.jsonl"),
            mirror=self.flight.record if self.flight is not None else None,
        )
        self._server: "MetricsHTTPServer | None" = None
        self._exporter: "PromFileExporter | None" = None
        self._sampler: "ResourceSampler | None" = None
        try:
            self._init_instruments(cfg, probes)
            if cfg.publish:
                self._init_fleet(cfg, publish_probes)
        except BaseException:
            # a half-built telemetry bundle must not leak the event fd /
            # exporter thread / metrics port into the caller's process
            self._release()
            raise

    def _release(self) -> None:
        """Tear the bundle down in reverse acquisition order — ONE copy
        shared by the construction guard and :meth:`close`.  The event-fd
        close rides the innermost finally so a server/exporter/sampler
        stop that ALSO fails cannot skip it (LT008)."""
        try:
            # fleet loop first: it emits into the event log and reads
            # the registry, both of which the later steps tear down
            self._stop_fleet()
        finally:
            try:
                if self._sampler is not None:
                    self._sampler.stop()
                    self._sampler = None
            finally:
                try:
                    if self._server is not None:
                        self._server.stop()
                        self._server = None
                finally:
                    try:
                        if self._exporter is not None:
                            self._exporter.stop()
                            self._exporter = None
                    finally:
                        self.events.close()

    def _init_instruments(self, cfg: ServeConfig, probes=None) -> None:
        self.registry = MetricsRegistry()
        r = self.registry
        self._queue_depth = r.gauge(
            "lt_serve_queue_depth", "jobs queued awaiting the dispatcher"
        )
        self._running = r.gauge(
            "lt_serve_running", "1 while a job is executing, else 0"
        )
        self._submitted = r.counter(
            "lt_serve_jobs_submitted_total", "jobs admitted into the queue"
        )
        self._rejections = r.counter(
            "lt_serve_rejections_total",
            "submissions refused by admission control (429-style)",
        )
        self._job_hist = r.histogram(
            "lt_serve_job_seconds",
            "job latency, submit to terminal state",
            buckets=_JOB_BUCKETS,
        )
        self._prog_hits = r.counter(
            "lt_serve_program_hits_total",
            "runs admitted warm (zero jit compiles)",
        )
        self._prog_misses = r.counter(
            "lt_serve_program_misses_total",
            "runs that compiled their program set (cold)",
        )
        self._prog_compile_s = r.counter(
            "lt_serve_compile_seconds_total",
            "seconds spent compiling program sets on cache misses",
        )
        self._warm_ratio = r.gauge(
            "lt_serve_warm_hit_ratio",
            "program-cache hits / (hits + misses) over the server's life",
        )
        # per-job SLO accounting: the latency split and the deadline
        # verdict (job_slo events carry the same numbers per job)
        self._queue_wait_hist = r.histogram(
            "lt_serve_queue_wait_seconds",
            "job queue wait, submit to dispatch",
            buckets=_JOB_BUCKETS,
        )
        self._exec_hist = r.histogram(
            "lt_serve_exec_seconds",
            "job execution, dispatch to terminal state",
            buckets=_JOB_BUCKETS,
        )
        self._slo_met = r.counter(
            "lt_slo_met_total",
            "terminal jobs inside their deadline_s (or with none set)",
        )
        self._slo_missed = r.counter(
            "lt_slo_missed_total",
            "terminal jobs past their deadline_s (accounting, not "
            "enforcement — the job still ran to its terminal state)",
        )
        self._slo_burn = r.gauge(
            "lt_slo_burn_rate",
            f"fraction of the last {_SLO_WINDOW_JOBS} DEADLINED "
            "terminal jobs that missed their deadline (jobs without a "
            "deadline_s never enter the window)",
        )
        # cross-job continuous batching (serve/batching): how often the
        # dispatcher coalesces, how much it coalesces, and how full the
        # shared launches run (batch_launch/batch_demux events carry the
        # same numbers per batch)
        self._batch_launches = r.counter(
            "lt_batch_launches_total",
            "shared launches coalescing >= 2 same-affinity jobs",
        )
        self._batch_jobs_coalesced = r.counter(
            "lt_batch_jobs_coalesced_total",
            "jobs that shared a launch (leader + members, per batch)",
        )
        self._batch_demux_tiles = r.counter(
            "lt_batch_demux_tiles_total",
            "durable tile artifacts demuxed to batch members' manifests",
        )
        self._batch_occupancy = r.gauge(
            "lt_batch_occupancy",
            "useful px / padded px of the most recent shared launch",
        )
        #: burn-rate window: the last N deadlined terminal jobs' met
        #: verdicts.  A dedicated deque, NOT the flight ring — one busy
        #: job's tile events would evict every prior ``job_slo`` record
        #: from the ring, collapsing the burn denominator to the job
        #: just ended.  Deadline-scoped, like obs_report's hit_rate: a
        #: no-deadline job is ``met`` by definition, and 99 of those
        #: must not dilute one missed deadline into burn 0.01.
        self._slo_window: collections.deque = collections.deque(
            maxlen=_SLO_WINDOW_JOBS
        )
        self._jobs_done: dict[str, Any] = {}
        self._prog_lock = threading.Lock()
        self._last_prog = {"hits": 0, "misses": 0, "compile_s": 0.0}
        self.events.run_start(
            fingerprint="serve",
            process_index=0,
            process_count=1,
            tiles_total=0,
            tiles_todo=0,
            tiles_skipped_resume=0,
            mesh_devices=0,
            impl="serve",
        )
        self._server = (
            MetricsHTTPServer(
                self.registry, cfg.metrics_port, host=cfg.metrics_host
            )
            if cfg.metrics_port is not None
            else None
        )
        try:
            self._exporter = PromFileExporter(
                self.registry,
                os.path.join(cfg.workdir, "metrics.prom"),
                interval_s=cfg.metrics_interval_s,
            ).start()
        except BaseException:
            # exporter construction/first-write failing after the port
            # bound: release the server HERE (locality) and mark it
            # released so __init__'s guard only owns the event fd
            if self._server is not None:
                self._server.stop()
                self._server = None
            raise
        if self.flight is not None:
            # started LAST (after run_start, so the stream still opens
            # its scope) — flight_sample events ride the normal event
            # log into the file AND the ring
            try:
                self._sampler = ResourceSampler(
                    self.events.emit, cfg.sampler_interval_s, probes=probes
                ).start()
            except BaseException:
                # sampler-thread start failing after the exporter/server
                # exist: release them HERE (locality, like the exporter
                # guard above) so __init__'s guard only owns the event
                # fd; telescoped so an exporter-stop failure cannot skip
                # the server release
                try:
                    if self._exporter is not None:
                        self._exporter.stop()
                        self._exporter = None
                finally:
                    if self._server is not None:
                        self._server.stop()
                        self._server = None
                raise

    def _stop_fleet(self) -> None:
        """Stop the fleet loop, flush the terminal snapshot, close the
        history ring.  Idempotent; called from :meth:`close` BEFORE the
        terminal events (so ``run_done`` stays the scope's tail — the
        sampler convention) and again from :meth:`_release` for the
        construction-guard path."""
        self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=10)
            self._fleet_thread = None
        if self._publisher is not None:
            self._publisher.stop()
            self._publisher = None
        if self.history is not None:
            self.history.close()
            self.history = None

    # -- the fleet telemetry plane (obs publish/aggregate/history/alerts) --
    #: history read window for alert evaluation, seconds — comfortably
    #: above any sane rule window so rate()/absence rules always see
    #: their full span
    _FLEET_HISTORY_S = 600.0

    def _init_fleet(self, cfg: ServeConfig, publish_probes) -> None:
        from land_trendr_tpu.obs.alerts import (
            DEFAULT_RULES,
            AlertEngine,
            load_rules,
        )
        from land_trendr_tpu.obs.history import HistoryRing
        from land_trendr_tpu.obs.publish import (
            TelemetryPublisher,
            telemetry_dir,
        )

        r = self.registry
        self._alerts_fired = r.counter(
            "lt_alerts_fired_total",
            "alert-rule firing transitions (obs/alerts over the fleet "
            "history)",
        )
        self._alerts_resolved = r.counter(
            "lt_alerts_resolved_total", "alert-rule resolved transitions"
        )
        self._alerts_firing = r.gauge(
            "lt_alerts_firing", "alert rules currently firing"
        )
        self._fleet_hosts = r.gauge(
            "lt_fleet_hosts", "snapshots folded into the latest pod view"
        )
        self._fleet_stale = r.gauge(
            "lt_fleet_stale_hosts", "hosts past their staleness bound"
        )
        self._telemetry_dir = cfg.telemetry_dir or telemetry_dir(cfg.workdir)
        self._publisher = TelemetryPublisher(
            self._telemetry_dir,
            self.registry,
            probes=publish_probes,
            interval_s=cfg.publish_interval_s,
            kind="serve",
        )
        try:
            self.history = HistoryRing(os.path.join(cfg.workdir, "history"))
            self.engine = AlertEngine(
                load_rules(cfg.alert_rules)
                if cfg.alert_rules
                else DEFAULT_RULES
            )
            self._fleet_interval_s = cfg.publish_interval_s
            self._fleet_thread = threading.Thread(
                target=self._fleet_loop, name="lt-fleet-loop", daemon=True
            )
            self._fleet_thread.start()
        except BaseException:
            # a later step raising (unwritable history dir, a rules file
            # deleted since config validation, thread-start failure) must
            # not leak the publisher/ring — released HERE (locality, the
            # exporter-guard pattern) so __init__'s guard stays a backstop
            self._stop_fleet()
            raise

    def _fleet_loop(self) -> None:
        # first beat right away (a short-lived server still folds once),
        # then every publish_interval_s until _release sets the stop
        while True:
            try:
                self.fleet_beat()
            except Exception:
                # a sick beat (full disk on the emit, FS churn mid-fold)
                # skips — the fleet plane must never take down the
                # server it watches
                log.debug("fleet beat failed", exc_info=True)
            if self._fleet_stop.wait(self._fleet_interval_s):
                return

    def fleet_beat(self, now: "float | None" = None) -> dict:
        """One fleet beat: publish own snapshot → fold the shared dir →
        append the pod sample to the history ring → evaluate alert
        rules → emit ``alert`` transitions + one ``fleet_sample``.
        Called from the loop thread (and directly by tests, with a
        pinned ``now`` for determinism); returns the pod view."""
        from land_trendr_tpu.obs import aggregate

        if now is None:
            now = time.time()
        try:
            self._publisher.publish_now()
        except Exception:
            pass  # a skipped beat is staleness, never a failed server
        # newer_than bounds how long a departed host haunts the fold: a
        # restarted replica's dead predecessor (same workdir, new pid)
        # reads STALE — and alerts — for the history window, then drops
        # to listed-but-excluded instead of double-counting its counters
        # and paging forever after every routine restart
        view = aggregate.fold_dir(
            self._telemetry_dir,
            now=now,
            newer_than=now - self._FLEET_HISTORY_S,
        )
        sample = aggregate.pod_sample(view)
        try:
            self.history.append(sample)
        except Exception:
            pass  # one lost sample (history.append seam, FS pressure)
        samples, _ = self.history.read(newer_than=now - self._FLEET_HISTORY_S)
        transitions = self.engine.evaluate(samples, now)
        for tr in transitions:
            self.events.emit("alert", **tr)
            if tr["state"] == "firing":
                self._alerts_fired.inc()
            else:
                self._alerts_resolved.inc()
        active = self.engine.active()
        counts = view["counts"]
        with self._fleet_lock:
            self._active_alerts = active
            self._fleet_counts = {
                "folded": counts["folded"],
                "stale": counts["stale"],
                "corrupt": counts["corrupt"],
            }
        self._alerts_firing.set(len(active))
        self._fleet_hosts.set(counts["folded"])
        self._fleet_stale.set(counts["stale"])
        self.events.emit(
            "fleet_sample",
            hosts=counts["folded"],
            stale_hosts=counts["stale"],
            corrupt_snaps=counts["corrupt"],
            alerts_firing=len(active),
            history_samples=len(samples),
        )
        return view

    def active_alerts(self) -> list:
        """Currently-firing alerts (JSON-safe; ``/healthz``, the
        publisher's ``state.alerts`` block, ``lt top``)."""
        with self._fleet_lock:
            return list(self._active_alerts)

    def fleet_counts(self) -> dict:
        with self._fleet_lock:
            return dict(self._fleet_counts)

    def _done_counter(self, status: str):
        c = self._jobs_done.get(status)
        if c is None:
            c = self._jobs_done[status] = self.registry.counter(
                "lt_serve_jobs_done_total",
                "jobs reaching a terminal state, by status",
                labels={"status": status},
            )
        return c

    # -- server hooks ------------------------------------------------------
    def job_submitted(self, job: Job, queue_depth: int) -> None:
        self.events.emit(
            "job_submitted",
            job_id=job.job_id,
            trace_id=job.trace_id,
            tenant=job.request.tenant,
            priority=job.request.priority,
            queue_depth=queue_depth,
            source=job.source,
        )
        self._submitted.inc()
        self._queue_depth.set(queue_depth)

    def job_rejected(
        self,
        reason: str,
        queue_depth: int,
        tenant: "str | None" = None,
    ) -> None:
        fields: dict = {}
        if tenant:
            fields["tenant"] = tenant
        self.events.emit(
            "job_rejected", reason=reason, queue_depth=queue_depth, **fields
        )
        self._rejections.inc()

    def job_start(self, job: Job, wait_s: float, queue_depth: int) -> None:
        self.events.emit(
            "job_start",
            job_id=job.job_id,
            trace_id=job.trace_id,
            tenant=job.request.tenant,
            wait_s=round(wait_s, 6),
        )
        self._running.set(1)
        self._queue_depth.set(queue_depth)

    def job_done(self, job: Job, wall_s: float) -> None:
        fields: dict = {}
        if job.error:
            fields["error"] = job.error
        quarantined = (job.summary or {}).get("tiles_quarantined")
        if quarantined:
            fields["tiles_quarantined"] = len(quarantined)
        self.events.emit(
            "job_done",
            job_id=job.job_id,
            trace_id=job.trace_id,
            status=job.state,
            wall_s=round(wall_s, 6),
            **fields,
        )
        self._running.set(0)
        # the exemplar closes the metrics→traces loop: the latency
        # bucket this job landed in remembers its trace_id, so "the
        # p99 bucket" resolves to requests lt_request can assemble
        self._job_hist.observe(wall_s, exemplar=job.trace_id or None)
        self._done_counter(job.state).inc()

    def job_slo(self, job: Job, slo: dict) -> None:
        """One terminal job's SLO accounting: the ``job_slo`` event plus
        the latency-split histograms, met/missed counters, and the burn
        rate over the last ``_SLO_WINDOW_JOBS`` deadlined terminal
        jobs."""
        self.events.emit(
            "job_slo",
            job_id=job.job_id,
            trace_id=job.trace_id,
            tenant=job.request.tenant,
            **slo,
        )
        ex = job.trace_id or None
        self._queue_wait_hist.observe(slo["queue_wait_s"], exemplar=ex)
        self._exec_hist.observe(slo["exec_s"], exemplar=ex)
        (self._slo_met if slo["met"] else self._slo_missed).inc()
        if "deadline_s" in slo:
            self._slo_window.append(bool(slo["met"]))
            window = list(self._slo_window)
            self._slo_burn.set(window.count(False) / len(window))

    def batch_launch(self, job: Job, stats: dict) -> None:
        """One coalesced launch: stamped with the LEADER's identity so
        blame attribution keeps partitioning each request exactly —
        members get their own ``batch_demux`` on the same scope."""
        self.events.emit(
            "batch_launch",
            job_id=job.job_id,
            trace_id=job.trace_id,
            jobs=int(stats["jobs"]),
            tiles=int(stats["tiles"]),
            padded_px=int(stats["padded_px"]),
            occupancy=float(stats["occupancy"]),
            window_wait_s=float(stats["window_wait_s"]),
        )
        self._batch_launches.inc()
        self._batch_jobs_coalesced.inc(int(stats["jobs"]))
        self._batch_occupancy.set(float(stats["occupancy"]))

    def batch_demux(self, job: Job, tiles: int) -> None:
        """One member's share of a shared launch, stamped with the
        MEMBER's identity (its run scope then resumes over the demuxed
        manifest with near-zero device work)."""
        self.events.emit(
            "batch_demux",
            job_id=job.job_id,
            trace_id=job.trace_id,
            tiles=int(tiles),
        )
        self._batch_demux_tiles.inc(int(tiles))

    def profile_captured(
        self,
        ok: bool,
        duration_s: float,
        path: str,
        error: "str | None" = None,
        nbytes: "int | None" = None,
    ) -> None:
        """One on-demand profiler capture attempt (POST /debug/profile);
        a failed capture is an event with ``ok=false``, never a failed
        job or server."""
        fields: dict = {}
        if error:
            fields["error"] = str(error)
        if nbytes is not None:
            fields["bytes"] = int(nbytes)
        self.events.emit(
            "profile_captured",
            ok=bool(ok),
            duration_s=round(float(duration_s), 6),
            path=path,
            **fields,
        )

    def program_cache(self, stats: dict) -> None:
        """Refresh the warm-ratio instruments from the server-wide
        totals (called after every job; the terminal aggregate event is
        emitted once at :meth:`close`).  Counters advance by delta —
        ``stats`` is cumulative."""
        with self._prog_lock:
            last = self._last_prog
            self._prog_hits.inc(stats.get("hits", 0) - last["hits"])
            self._prog_misses.inc(
                stats.get("misses", 0) - last["misses"]
            )
            self._prog_compile_s.inc(
                max(0.0, stats.get("compile_s", 0.0) - last["compile_s"])
            )
            self._last_prog = {
                "hits": stats.get("hits", 0),
                "misses": stats.get("misses", 0),
                "compile_s": stats.get("compile_s", 0.0),
            }
        hits, misses = stats.get("hits", 0), stats.get("misses", 0)
        if hits + misses:
            self._warm_ratio.set(hits / (hits + misses))

    def close(self, status: str, wall_s: float, stats: dict) -> None:
        try:
            # fleet loop down FIRST: a beat landing between the terminal
            # events below and _release would append fleet_sample/alert
            # lines behind the scope's run_done
            self._stop_fleet()
        except Exception as exc:
            log.error("fleet-loop stop failed: %s", exc)
        try:
            self.events.emit(
                "program_cache",
                hits=int(stats.get("hits", 0)),
                misses=int(stats.get("misses", 0)),
                compile_s=round(float(stats.get("compile_s", 0.0)), 6),
                keys=int(stats.get("keys", 0)),
            )
            self.events.emit(
                "run_done",
                status=status,
                tiles_done=0,
                pixels=0,
                wall_s=round(wall_s, 3),
                px_per_s=0.0,
                fit_rate=0.0,
            )
        finally:
            self._release()


class SegmentationServer:
    """Long-lived segmentation server over one process's warm state."""

    def __init__(self, cfg: ServeConfig) -> None:
        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        self._lock = threading.Lock()
        # the condition WRAPS self._lock (same lock object): guarded
        # state is always mutated under `with self._lock`, and the
        # condition is only used for wait/notify while holding it
        self._cond = threading.Condition(self._lock)
        self._jobs: dict[str, Job] = {}
        self._queue: list = []  # heap of (-priority, seq, job_id)
        self._seq = 0
        self._queued = 0
        self._terminal = 0
        self._stopping = False
        #: shutdown has BEGUN (vs _stopping = drain requested): new
        #: profiler captures are refused past this point, and the
        #: teardown waits out the ones already in flight
        self._closing = False
        self._captures = 0
        self._running_id: "str | None" = None
        self.programs = ProgramCache()
        #: request-level affinity keys of jobs whose Run actually
        #: executed here (their programs are resident in this process's
        #: jit cache) — recency-ordered, bounded, exposed on /healthz so
        #: a warm-affinity router can rebuild its table from health
        #: probes alone (adoption, router restart)
        self._warm_keys: "collections.OrderedDict[str, float]" = (
            collections.OrderedDict()
        )
        #: recent TERMINAL requests (trace id, latency split, status) —
        #: the /debug/requests window, newest last, bounded by
        #: ``request_ring`` (mutated under the server lock)
        self._recent_requests: collections.deque = collections.deque(
            maxlen=cfg.request_ring  # 0 = an always-empty ring
        )

        # every teardown-touched handle exists BEFORE anything that can
        # fail: _shutdown_shared must be callable from any depth of a
        # partially finished construction.  (Previously a busy
        # --serve-port died in the cleanup path on the not-yet-bound
        # self._dropbox_stop — an AttributeError MASKING the bind error —
        # and a telemetry/fault-arming failure leaked the store's mmaps
        # plus its process-global cache attachment; LT008 found both.)
        self.store = None
        self.telemetry = None
        self._fault_plan = None
        #: the tuning-profile resolution of the most recent job whose
        #: config carried "auto" knobs (key + age + source) — the
        #: /healthz + fleet-snapshot fact satellite tooling renders
        self._tune_info: "dict | None" = None
        self._httpd = None
        self._http_thread = None
        self._dropbox_stop = threading.Event()
        self._dropbox_thread = None
        self._t0 = time.time()

        try:
            # the shared warm state every job rides: ONE process-wide
            # cache configuration (the server owns it; Run skips
            # reconfiguring when handed a shared store) and ONE
            # persistent ingest store
            if cfg.ingest_store_mb:
                from land_trendr_tpu.io.blockstore import BlockStore

                self.store = BlockStore(
                    cfg.ingest_store_dir
                    or os.path.join(cfg.workdir, "ingest_store"),
                    budget_bytes=cfg.ingest_store_mb << 20,
                )
            blockcache.configure(
                budget_bytes=cfg.feed_cache_mb << 20,
                workers=cfg.decode_workers,
                store=self.store,
            )

            self.telemetry = (
                _ServeTelemetry(
                    cfg,
                    probes=self._sampler_probes,
                    publish_probes=self._fleet_probes,
                )
                if cfg.telemetry
                else None
            )

            # one process-wide fault plan shared by every job (soak
            # mode); jobs carrying their own schedule are rejected by
            # the Run
            if cfg.fault_schedule:
                self._fault_plan = faults.activate(
                    faults.parse_schedule(cfg.fault_schedule)
                )
                log.warning(
                    "serve fault injection ACTIVE (%s) — this is a "
                    "soak run", cfg.fault_schedule,
                )

            self._httpd = _JobAPIServer(
                (cfg.serve_host, cfg.serve_port), self
            )
            self.port = int(self._httpd.server_address[1])
            http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="lt-serve-http",
                daemon=True,
            )
            # bound only AFTER a successful start: _shutdown_shared keys
            # httpd.shutdown() on it, and socketserver's shutdown()
            # deadlocks forever unless serve_forever is actually running
            http_thread.start()
            self._http_thread = http_thread

            if cfg.dropbox_dir:
                os.makedirs(cfg.dropbox_dir, exist_ok=True)
                self._dropbox_thread = threading.Thread(
                    target=self._dropbox_loop,
                    name="lt-serve-dropbox",
                    daemon=True,
                )
                self._dropbox_thread.start()
        except BaseException:
            # construction failed partway: tear down exactly what exists
            # — store close + cache detach, armed fault plan, telemetry,
            # API socket — so nothing outlives the failed server
            self._shutdown_shared(status="aborted")
            raise
        log.info(
            "serving on %s:%d (queue depth %d, %s)",
            cfg.serve_host, self.port, cfg.serve_queue_depth,
            f"max_jobs={cfg.max_jobs}" if cfg.max_jobs else "unbounded",
        )

    def _sampler_probes(self) -> dict:
        """Host gauges for the flight sampler's ``flight_sample``
        events: queue/admission state, warm-program residency, cache
        occupancy, and — while a job runs — its pipeline backlogs."""
        with self._lock:
            out = {
                "queue_depth": self._queued,
                "running": 1 if self._running_id is not None else 0,
                "jobs_total": len(self._jobs),
            }
            running = (
                self._jobs.get(self._running_id)
                if self._running_id is not None
                else None
            )
            run = running.run if running is not None else None
        out["warm_program_count"] = int(
            self.programs.stats().get("keys", 0)
        )
        out.update(blockcache.occupancy_probe())
        if run is not None:
            # liveness half of straggler detection for serve jobs: the
            # SERVER's sampler thread sweeps the running job's in-flight
            # tiles, so a tile wedging the job's own device wait is
            # flagged while it runs (the detector flags each tile once).
            # Only while the run is live — its phase flips to
            # done/aborted at the top of teardown, BEFORE the terminal
            # run_done, so scanning a finishing run here would append
            # verdicts behind the scope's terminal event
            detector = getattr(run, "straggler", None)
            p = getattr(run, "progress", None)
            if detector is not None and p is not None and p.get(
                "phase"
            ) not in ("done", "aborted"):
                detector.scan()
            if p is not None:
                for k in (
                    "feed_backlog", "write_backlog", "fetch_backlog",
                    "upload_backlog", "stragglers", "tiles_stolen",
                    "tiles_speculated", "batch_jobs", "batch_tiles",
                ):
                    out[k] = int(p.get(k, 0))
                out["batch_occupancy"] = float(
                    p.get("batch_occupancy", 0.0)
                )
        return out

    def _fleet_probes(self) -> dict:
        """The ``state`` block of this replica's fleet snapshot
        (obs/publish): queue/job facts plus the currently-firing alerts
        — so ``lt_fleet`` and ``lt top --dir`` surface a replica's
        alerts straight from the shared directory, no HTTP needed."""
        with self._lock:
            progress = {
                "queue_depth": self._queued,
                "running": 1 if self._running_id is not None else 0,
                "jobs_total": len(self._jobs),
                "jobs_terminal": self._terminal,
            }
            tune_info = self._tune_info
        out: dict = {"progress": progress}
        if tune_info is not None:
            out["tune"] = tune_info
        tel = self.telemetry
        if tel is not None:
            out["alerts"] = tel.active_alerts()
        return out

    # -- admission ---------------------------------------------------------
    def submit(self, payload: dict, source: str = "http") -> dict:
        """One submission through admission control; returns the queued
        job's status snapshot or raises :class:`Rejection`.

        The ``serve.submit`` fault seam fires here: an injected
        submission fault is a rejected request and a live server, never
        a dead one.
        """
        tenant = (
            payload.get("tenant", "default")
            if isinstance(payload, dict)
            else None
        )
        if not isinstance(tenant, str):
            # an adversarial non-string tenant must not leak into the
            # job_rejected event (its tenant field is schema-typed str)
            tenant = None
        req = None
        rejection: "tuple[int, str, str] | None" = None
        try:
            faults.check("serve.submit")
            req = JobRequest.from_payload(payload)
        except ValueError as e:
            rejection = (400, "bad_request", str(e))
        except Exception as e:  # the injected-fault shape
            rejection = (503, "submit_error", str(e))
        snap = depth = job = None
        if rejection is None:
            with self._lock:
                depth = self._queued
                if self._stopping:
                    rejection = (503, "shutting_down", "server is draining")
                elif depth >= self.cfg.serve_queue_depth:
                    rejection = (
                        429,
                        "queue_full",
                        f"queue depth {depth} at the configured bound "
                        f"{self.cfg.serve_queue_depth}; retry later",
                    )
                else:
                    inflight = sum(
                        1
                        for j in self._jobs.values()
                        if j.request.tenant == req.tenant
                        and j.state in ("queued", "running")
                    )
                    if inflight >= self.cfg.tenant_max_inflight:
                        rejection = (
                            429,
                            "tenant_cap",
                            f"tenant {req.tenant!r} has {inflight} job(s) "
                            f"in flight at the configured bound "
                            f"{self.cfg.tenant_max_inflight}; retry later",
                        )
                if rejection is None:
                    self._seq += 1
                    job_id = f"job-{os.getpid()}-{self._seq:05d}"
                    job = Job(
                        job_id=job_id,
                        request=req,
                        source=source,
                        # the fleet router minted one at ITS admission
                        # (the forward payload carries it, re-routes
                        # included); a direct job mints here — either
                        # way every event of the job's journey carries
                        # ONE correlation id
                        trace_id=req.trace_id or uuid.uuid4().hex[:16],
                    )
                    job_root = os.path.join(
                        self.cfg.workdir, "jobs", job_id
                    )
                    job.workdir = req.workdir or os.path.join(
                        job_root, "work"
                    )
                    job.out_dir = req.out_dir or os.path.join(
                        job_root, "out"
                    )
                    self._jobs[job_id] = job
                    heapq.heappush(
                        self._queue, (-req.priority, self._seq, job_id)
                    )
                    self._queued += 1
                    depth = self._queued
                    snap = job.status_locked()
                    self._cond.notify_all()
        # telemetry emits happen OUTSIDE the server lock (the event log
        # has its own) — the admission path never holds both
        if rejection is not None:
            status, reason, detail = rejection
            if depth is None:
                with self._lock:
                    depth = self._queued
            log.warning(
                "submission rejected (%s, tenant=%s)", reason,
                req.tenant if req is not None else tenant,
            )
            if self.telemetry is not None:
                self.telemetry.job_rejected(
                    reason, depth,
                    req.tenant if req is not None else tenant,
                )
            raise Rejection(status, reason, detail)
        if self.telemetry is not None:
            self.telemetry.job_submitted(job, depth)
        return snap

    def _note_warm_key_locked(self, key: str) -> None:
        """Record one executed shape's affinity key (caller holds the
        lock); recency-ordered and bounded at ``_WARM_KEYS_MAX``."""
        self._warm_keys[key] = time.time()
        self._warm_keys.move_to_end(key)
        while len(self._warm_keys) > _WARM_KEYS_MAX:
            self._warm_keys.popitem(last=False)

    # -- status / cancel ---------------------------------------------------
    def job_status(self, job_id: str) -> "dict | None":
        with self._lock:
            job = self._jobs.get(job_id)
            return job.status_locked() if job is not None else None

    def jobs(self) -> list:
        with self._lock:
            return [j.status_locked() for j in self._jobs.values()]

    def stats(self) -> dict:
        with self._lock:
            snap = {
                "queue_depth": self._queued,
                "running": self._running_id,
                "jobs_terminal": self._terminal,
                "jobs_total": len(self._jobs),
                # the request-level warm-affinity keys (newest last,
                # bounded): what a warm-affinity router joins its own
                # JobRequest.affinity_key() against — warm_program_count
                # alone names no shapes, so a router could not rebuild
                # its table from it
                "warm_keys": list(self._warm_keys),
                # which tuning profile (key/age/source) the last
                # auto-knob job resolved through; None = no tuned job
                # yet (or no store configured — the untuned half of a
                # mixed fleet shows as exactly that)
                "tune": self._tune_info,
            }
        snap["program_cache"] = self.programs.stats()
        # load-balancer-grade health facts ride /healthz directly so an
        # LB check need not scrape (and parse) the Prometheus exposition
        snap["warm_program_count"] = int(
            snap["program_cache"].get("keys", 0)
        )
        snap["uptime_s"] = round(time.time() - self._t0, 3)
        tel = self.telemetry
        if tel is not None and self.cfg.publish:
            # fleet facts ride /healthz directly (like the warm-program
            # count): an LB/operator check sees firing alerts and stale
            # hosts without scraping the exposition
            snap["alerts"] = tel.active_alerts()
            snap["fleet"] = tel.fleet_counts()
        return snap

    # -- the /debug surface ------------------------------------------------
    def flight_snapshot(
        self, n: "int | None" = None, trace_id: "str | None" = None
    ) -> "dict | None":
        """The flight ring's recent window (None when telemetry or the
        ring is off): ring stats plus the newest ``n`` (default: all
        held) mirrored event records, oldest first.  ``held`` preserves
        the ring's occupancy (stats' integer ``events``), which the
        record list — possibly truncated to ``n`` — replaces.  With
        ``trace_id``, only records stamped with that id are kept (the
        ring mirrors every emit, so a job's whole recent story filters
        out of the shared window) — the filter applies BEFORE the ``n``
        truncation, so "the last 50 events of THIS trace" means what it
        says."""
        flight = self.telemetry.flight if self.telemetry is not None else None
        if flight is None:
            return None
        stats = flight.stats()
        stats["held"] = stats["events"]
        recs = flight.snapshot()
        if trace_id is not None:
            recs = [
                r for r in recs
                if isinstance(r, dict) and r.get("trace_id") == trace_id
            ]
            stats["trace_id"] = trace_id
            stats["matched"] = len(recs)
        if n is not None and n > 0:
            recs = recs[-n:]
        stats["events"] = recs
        return stats

    def _note_request_locked(self, job: Job, slo: dict) -> None:
        """Fold one terminal job into the /debug/requests ring (caller
        holds the server lock): the trace id, the replica-side latency
        split (this server IS the replica — queue wait + exec is its
        whole view), and the terminal status."""
        self._recent_requests.append({
            "trace_id": job.trace_id,
            "job_id": job.job_id,
            "tenant": job.request.tenant,
            "status": job.state,
            "latency_s": slo["latency_s"],
            "blame": {
                "replica_queue": slo["queue_wait_s"],
                "exec": slo["exec_s"],
            },
            "finished_t": job.finished_t,
        })

    def debug_requests(self) -> list:
        """Recent terminal requests, slowest first — the human half of
        the exemplar loop (each row's ``trace_id`` is assemblable via
        ``tools/lt_request.py``)."""
        with self._lock:
            recent = list(self._recent_requests)
        recent.sort(
            key=lambda r: -(
                r["latency_s"]
                if isinstance(r["latency_s"], (int, float)) else 0.0
            )
        )
        return recent

    def debug_jobs(self) -> list:
        """Per-job live state: the status snapshot plus — for a running
        job — the Run's progress (phase, tiles done/total, retry count,
        pipeline backlog depths)."""
        with self._lock:
            pairs = [(j, j.status_locked()) for j in self._jobs.values()]
        for job, snap in pairs:
            run = job.run
            if run is not None and snap["state"] == "running":
                # point-in-time copy: progress keys are fixed at Run
                # construction, so the copy can never race a dict resize
                snap["progress"] = dict(run.progress)
        return [snap for _, snap in pairs]

    def capture_profile(self, duration_s: float) -> dict:
        """On-demand, duration-bounded profiler capture of the LIVE
        process (POST /debug/profile): whatever the dispatcher and its
        job do during the window is what the trace shows.  Never raises:
        a failed capture — the ``debug.profile`` fault seam, a
        concurrent capture, a profiler error mid-job — is an
        ``ok=false`` verdict (and a ``profile_captured`` event), not a
        failed job or server."""
        t0 = time.perf_counter()
        logdir = os.path.join(
            self.cfg.workdir, "profiles",
            f"profile-{int(time.time() * 1000)}-{os.getpid()}",
        )
        with self._lock:
            if self._closing:
                # shutdown in progress: a capture started now could not
                # flush before the process (and the native profiler
                # session) tears down under it
                return {
                    "ok": False,
                    "path": logdir,
                    "duration_s": 0.0,
                    "error": "shutting_down: server is tearing down",
                }
            self._captures += 1
        try:
            try:
                faults.check("debug.profile")
                from land_trendr_tpu.utils.profiling import capture_profile

                snap = {"ok": True, **capture_profile(logdir, duration_s)}
            except Exception as e:
                snap = {
                    "ok": False,
                    "path": logdir,
                    "duration_s": round(time.perf_counter() - t0, 6),
                    "error": f"{type(e).__name__}: {e}",
                }
            # the event emit happens BEFORE the _captures release: the
            # shutdown drain cannot close telemetry while we still hold
            # a capture slot, so the emit can never race the teardown.
            # Best-effort beyond that (a full disk must not turn the
            # capture verdict into a lost HTTP response).
            telemetry = self.telemetry
            if telemetry is not None:
                try:
                    telemetry.profile_captured(
                        snap["ok"],
                        snap["duration_s"],
                        snap["path"],
                        error=snap.get("error"),
                        nbytes=snap.get("bytes"),
                    )
                except Exception as exc:
                    log.error("profile_captured emit failed: %s", exc)
        finally:
            with self._lock:
                self._captures -= 1
                self._cond.notify_all()
        return snap

    def cancel(self, job_id: str) -> "dict | None":
        """Cancel one job: a queued job goes terminal immediately; a
        running job's cancel event unwinds its Run through the abort
        path (manifest resumable)."""
        finished = None
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job.state == "queued":
                job.state = "cancelled"
                job.finished_t = time.time()
                self._queued -= 1
                self._terminal += 1
                finished = job
            elif job.state == "running":
                job.cancel.set()
            snap = job.status_locked()
        if finished is not None:
            with self._lock:
                slo = finished.slo_locked()
                self._note_request_locked(finished, slo)
            if self.telemetry is not None:
                self.telemetry.job_done(
                    finished, finished.finished_t - finished.submitted_t
                )
                self.telemetry.job_slo(finished, slo)
            self._write_result(finished)
        with self._lock:
            self._cond.notify_all()
        return snap

    def stop(self) -> None:
        """Ask the dispatcher to shut down after the current job."""
        with self._lock:
            self._stopping = True
            self._cond.notify_all()

    # -- the dispatcher ----------------------------------------------------
    def serve_forever(self) -> None:
        """Drain jobs on THIS thread until stopped (or ``max_jobs``
        terminal states), then shut everything down."""
        status = "ok"
        try:
            while True:
                job = self._next_job()
                if job is None:
                    break
                self._run_job(job, batch=self._collect_batch(job))
        except BaseException:
            status = "aborted"
            raise
        finally:
            self._shutdown_shared(status=status)

    def _drained_locked(self) -> bool:
        """Caller holds the lock: the bounded mode's exit condition."""
        return (
            self.cfg.max_jobs is not None
            and self._terminal >= self.cfg.max_jobs
        )

    def _next_job(self) -> "Job | None":
        with self._lock:
            while True:
                if self._stopping or self._drained_locked():
                    return None
                while self._queue:
                    _, _, job_id = heapq.heappop(self._queue)
                    job = self._jobs[job_id]
                    if job.state != "queued":
                        continue  # cancelled while queued
                    job.state = "running"
                    job.started_t = time.time()
                    self._queued -= 1
                    self._running_id = job_id
                    return job
                self._cond.wait(timeout=0.2)

    def _batch_front_locked(self, key: str) -> "tuple[list, bool]":
        """The contiguous same-affinity front of the fairness-ordered
        queue (caller holds the lock): member candidates in pop order,
        plus whether a NON-matching job blocks the front.  Batching
        takes exactly the next jobs the scheduler would have run anyway
        — it changes packing, never fairness ordering."""
        members: list = []
        blocked = False
        for _, _, job_id in sorted(self._queue):
            job = self._jobs.get(job_id)
            if job is None or job.state != "queued":
                continue  # cancelled while queued (stale heap entry)
            req = job.request
            # a member must resume its manifest to see its demuxed
            # tiles; resume=False opts the job out of co-batching
            if req.affinity_key() == key and req.resume:
                if job.batch_demuxed:
                    # an earlier batch already filled its manifest:
                    # nothing left to demux into it — skip it (it pops
                    # before the members anyway and resumes solo)
                    continue
                members.append(job)
            else:
                blocked = True
                break
        return members, blocked

    def _collect_batch(self, leader: Job):
        """Collect the leader's batch (cross-job continuous batching):
        hold the window open up to ``batch_window_ms`` for
        same-affinity stragglers, closing EARLY when a non-matching job
        reaches the queue front (fairness must never wait on packing)
        or the queue is empty (a single-job fleet keeps today's path
        and today's latency).  Returns a
        :class:`~land_trendr_tpu.serve.batching.CrossJobBatch` or
        ``None`` (solo — the stock path)."""
        cfg = self.cfg
        if cfg.batch is False:
            return None
        if leader.batch_demuxed:
            # an earlier batch fully demuxed this job: its run is a
            # pure resume (every tile already durable), so a window
            # could only delay it — and a batch behind a no-work
            # leader demuxes nothing.  Solo, stock path.
            return None
        key = leader.request.affinity_key()
        deadline = time.monotonic() + cfg.batch_window_ms / 1000.0
        t0 = time.monotonic()
        with self._lock:
            while True:
                members, blocked = self._batch_front_locked(key)
                now = time.monotonic()
                if (
                    self._stopping
                    or blocked
                    or not members
                    or now >= deadline
                    or leader.cancel.is_set()
                ):
                    break
                self._cond.wait(timeout=min(deadline - now, 0.05))
        if not members:
            return None
        window_wait_s = time.monotonic() - t0
        # the batch.pack seam: an injected pack failure excludes THAT
        # candidate from the batch — it runs solo in its normal queue
        # turn; the batch and its other members live
        packed = []
        for m in members:
            try:
                faults.check("batch.pack")
                packed.append(m)
            except Exception as e:
                log.warning(
                    "batch pack excluded job %s: %s (it runs solo)",
                    m.job_id, e,
                )
        if not packed:
            return None
        from land_trendr_tpu.serve.batching import CrossJobBatch

        batch = CrossJobBatch(leader, packed)
        batch.window_wait_s = window_wait_s
        return batch

    def _open_stack(self, req: JobRequest):
        from land_trendr_tpu.ops.indices import required_bands

        bands = required_bands(req.index, tuple(req.ftv))
        if req.lazy:
            from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

            return open_stack_dir_c2_lazy(req.stack_dir, bands=bands)
        from land_trendr_tpu.runtime import load_stack_dir

        return load_stack_dir(req.stack_dir, bands=bands)

    def _run_job(self, job: Job, batch=None) -> None:
        from land_trendr_tpu.runtime import (
            Run,
            RunCancelled,
            StallError,
            TileRetriesExhausted,
            assemble_outputs,
        )

        req = job.request
        wait_s = job.started_t - job.submitted_t
        if self.telemetry is not None:
            with self._lock:
                depth = self._queued
            self.telemetry.job_start(job, wait_s, depth)
        log.info(
            "job %s start (tenant=%s, waited %.2fs)",
            job.job_id, req.tenant, wait_s,
        )

        timeout_s = (
            req.timeout_s
            if req.timeout_s is not None
            else self.cfg.job_timeout_s
        )
        timer = None
        if timeout_s is not None:
            timer = threading.Timer(timeout_s, self._timeout_job, [job])
            timer.daemon = True
            timer.start()

        state, error, summary, outputs = "error", None, None, None
        try:
            faults.check("serve.job")
            cfg = req.to_run_config(
                job.workdir, job.out_dir, telemetry=self.cfg.telemetry
            )
            if self.cfg.tune_store_dir and cfg.tune_store_dir is None:
                # the replica's shared tuning store: "auto" knobs in the
                # job's config resolve through it (a job naming its OWN
                # store keeps it — explicit wins, like the knobs)
                cfg = dataclasses.replace(
                    cfg, tune_store_dir=self.cfg.tune_store_dir
                )
            stack = self._open_stack(req)
            run = Run(
                stack,
                cfg,
                job_id=job.job_id,
                trace_id=job.trace_id,
                cancel=job.cancel,
                programs=self.programs,
                shared_store=self.store,
                # the server configured the process-wide cache once at
                # startup; per-job cache knobs must not clobber it
                shared_cache=True,
                # job events mirror into the SERVER's flight ring, so
                # /debug/flight shows live tile traffic; the run's
                # progress dict feeds /debug/jobs and the sampler
                flight=(
                    self.telemetry.flight
                    if self.telemetry is not None
                    else None
                ),
                # cross-job batching: every durable tile of this leader
                # run demuxes into its batch-mates' manifests (None on
                # the stock solo path)
                on_tile_durable=(
                    batch.demux_tile if batch is not None else None
                ),
            )
            job.run = run
            if batch is not None:
                from land_trendr_tpu.serve.batching import resolve_batch

                # "auto" resolves through the replica's tuning store
                # now that the scene shape is known; an explicit True
                # skips the store (the knob contract)
                if resolve_batch(
                    self.cfg.batch,
                    self.cfg.tune_store_dir,
                    (*stack.shape, stack.n_years),
                ):
                    stats = batch.open(
                        run,
                        max_tiles=self.cfg.batch_max_tiles,
                        window_wait_s=getattr(batch, "window_wait_s", 0.0),
                    )
                    if batch.members and self.telemetry is not None:
                        self.telemetry.batch_launch(job, stats)
                    if not batch.members:
                        # batch_max_tiles trimmed everyone: stock path
                        run.on_tile_durable = None
                        batch = None
                else:
                    run.on_tile_durable = None
                    batch = None
            if run.tune_info is not None:
                # which profile this replica's jobs resolve through —
                # surfaced on /healthz and the fleet snapshot so a mixed
                # tuned/untuned fleet is visible instead of silent
                with self._lock:
                    self._tune_info = dict(run.tune_info)
            summary = run.execute()
            # resuming needs the SAME manifest: fresh submissions get
            # fresh jobs/<id>/work dirs, so every retryable error spells
            # out the workdir the resubmission must pin
            resume_hint = (
                f"resubmit with \"workdir\": {job.workdir!r} to resume"
            )
            if summary.get("tiles_quarantined"):
                state = "retries_exhausted"
                error = (
                    f"{len(summary['tiles_quarantined'])} tile(s) "
                    f"quarantined after exhausting retries; {resume_hint}"
                )
            else:
                if req.assemble:
                    # the Run's RESOLVED config, not the submitted one: a
                    # store re-probed mid-job must not re-resolve "auto"
                    # knobs to different values at assembly time
                    outputs = assemble_outputs(stack, run.cfg)
                state = "done"
        except RunCancelled as e:
            state = "stalled" if job.timed_out else "cancelled"
            error = (
                f"job timeout after {timeout_s}s; manifest resumable — "
                f"resubmit with \"workdir\": {job.workdir!r} to resume"
                if job.timed_out
                else f"{e}; resubmit with \"workdir\": {job.workdir!r} "
                "to resume"
            )
        except StallError as e:
            state, error = "stalled", str(e)
        except TileRetriesExhausted as e:
            state, error = (
                "retries_exhausted",
                f"{e}; resubmit with \"workdir\": {job.workdir!r} to resume",
            )
        except (ValueError, TypeError, FileNotFoundError, NotADirectoryError) as e:
            state, error = "config_error", str(e)
        except Exception as e:
            # the residual class (and the serve.job fault seam's shape):
            # the JOB is terminal, the server and sibling jobs live on
            state, error = "error", f"{type(e).__name__}: {e}"
            log.exception("job %s failed", job.job_id)
        finally:
            if timer is not None:
                timer.cancel()

        if batch is not None:
            # per-member demux accounting, stamped with EACH member's
            # identity (blame attribution still partitions each request
            # exactly); emitted even after a leader failure — whatever
            # demuxed before the abort is durable, and each member's
            # own queued run completes the rest byte-identically
            for mjob, tiles, merr, complete in batch.finalize():
                if self.telemetry is not None:
                    self.telemetry.batch_demux(mjob, tiles)
                # a fully-demuxed member's queue turn is a pure resume:
                # flag it so the dispatcher never holds a batch window
                # for it (a batch behind a no-work leader demuxes
                # nothing — the window could only delay the flood)
                mjob.batch_demuxed = complete
                if merr:
                    log.info(
                        "batch member %s fell back to solo after %d "
                        "demuxed tile(s): %s", mjob.job_id, tiles, merr,
                    )

        with self._lock:
            job.state = state
            job.error = error
            job.summary = summary
            job.outputs = outputs
            job.finished_t = time.time()
            if summary is not None:
                # the Run executed here, so this shape's programs are
                # resident in the process jit cache: the key is warm for
                # any router reading /healthz
                self._note_warm_key_locked(req.affinity_key())
            # release the Run: it pins the job's whole decoded stack
            # (plus manifest/fetcher/uploader) — retained across
            # terminal jobs it would grow the long-lived server by a
            # full scene per job.  /debug/jobs only reads progress for
            # RUNNING jobs, so nothing observes it past this point.
            job.run = None
            self._terminal += 1
            self._running_id = None
            wall_s = job.finished_t - job.submitted_t
        log.info(
            "job %s %s in %.2fs%s",
            job.job_id, state, wall_s, f" ({error})" if error else "",
        )
        with self._lock:
            slo = job.slo_locked()
            self._note_request_locked(job, slo)
        if self.telemetry is not None:
            self.telemetry.job_done(job, wall_s)
            self.telemetry.job_slo(job, slo)
            self.telemetry.program_cache(self.programs.stats())
        self._write_result(job)
        with self._lock:
            self._cond.notify_all()

    def _timeout_job(self, job: Job) -> None:
        with self._lock:
            if job.state != "running":
                return
            job.timed_out = True
        log.warning(
            "job %s exceeded its timeout; cancelling (manifest stays "
            "resumable)", job.job_id,
        )
        job.cancel.set()

    # -- drop-box ----------------------------------------------------------
    def _dropbox_loop(self) -> None:
        cfg = self.cfg
        while not self._dropbox_stop.wait(cfg.dropbox_poll_s):
            try:
                names = sorted(os.listdir(cfg.dropbox_dir))
            except OSError:
                continue
            for name in names:
                if not name.endswith(".json") or name.endswith(
                    (".rejected.json", ".result.json")
                ):
                    continue
                path = os.path.join(cfg.dropbox_dir, name)
                claimed = path + ".claimed"
                try:
                    os.rename(path, claimed)  # atomic claim
                except OSError:
                    continue  # a sibling scanner (or the client) won
                self._submit_dropbox(path, claimed)

    def _submit_dropbox(self, orig: str, claimed: str) -> None:
        try:
            with open(claimed) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            self._write_json(
                orig + ".rejected.json",
                {"reason": "bad_request", "detail": f"unreadable: {e}"},
            )
            return
        try:
            snap = self.submit(payload, source="dropbox")
        except Rejection as e:
            self._write_json(
                orig + ".rejected.json",
                {"reason": e.reason, "detail": e.detail},
            )
            return
        with self._lock:
            self._jobs[snap["job_id"]].dropbox_path = orig

    def _write_result(self, job: Job) -> None:
        """Durable terminal-state snapshot for EVERY job:
        ``<workdir>/jobs/<job_id>/result.json`` (plus the drop-box
        sidecar for drop-box jobs).  A ``max_jobs`` server closes its
        API right after the last job goes terminal, so an HTTP client
        can lose the race to one final GET — the result file is the
        durable answer (and the crash-forensics record)."""
        with self._lock:
            snap = job.status_locked()
        job_root = os.path.join(self.cfg.workdir, "jobs", job.job_id)
        os.makedirs(job_root, exist_ok=True)
        self._write_json(os.path.join(job_root, "result.json"), snap)
        if job.dropbox_path:
            self._write_json(job.dropbox_path + ".result.json", snap)

    @staticmethod
    def _write_json(path: str, payload: dict) -> None:
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2, default=str)
            os.replace(tmp, path)
        except OSError as e:
            log.error("drop-box sidecar write failed (%s): %s", path, e)

    # -- shutdown ----------------------------------------------------------
    def _shutdown_shared(self, status: str) -> None:
        """Tear down the shared warm state (idempotent; the reverse of
        construction).  Jobs already terminal keep their durable
        manifests/outputs whatever happens here."""
        with self._lock:
            self._stopping = True
            self._closing = True
            self._cond.notify_all()
            # drain in-flight profiler captures BEFORE closing anything:
            # a drain-mode (--max-jobs) server otherwise exits while a
            # handler thread is inside the native profiler session —
            # observed as a SIGSEGV at interpreter teardown, and a lost
            # response for the client.  Bounded by the capture's own
            # duration ceiling plus flush slack; new captures are
            # refused once _closing is set, so this converges.
            deadline = time.monotonic() + _JobAPIHandler.MAX_PROFILE_S + 60
            while self._captures and time.monotonic() < deadline:
                self._cond.wait(timeout=1.0)
        self._dropbox_stop.set()
        httpd = getattr(self, "_httpd", None)
        thread = getattr(self, "_http_thread", None)
        if httpd is not None:
            if thread is not None:
                # shutdown() handshakes with a RUNNING serve_forever;
                # called before the loop thread ever started it waits
                # forever — a failed construction closes the socket only
                httpd.shutdown()
            httpd.server_close()
            self._httpd = None
        if thread is not None:
            thread.join(timeout=10)
            self._http_thread = None
        if self._dropbox_thread is not None:
            self._dropbox_thread.join(timeout=10)
            self._dropbox_thread = None
        if self.store is not None:
            try:
                self.store.close()
            except Exception as exc:
                log.error("ingest-store flush/close failed: %s", exc)
            blockcache.detach_store(self.store)
            self.store = None
        if self._fault_plan is not None:
            faults.set_observer(None)
            faults.deactivate()
            self._fault_plan = None
        if self.telemetry is not None:
            try:
                self.telemetry.close(
                    status, time.time() - self._t0, self.programs.stats()
                )
            except Exception as exc:
                log.error("serve telemetry close failed: %s", exc)
            # final flight dump AFTER close, so the terminal
            # program_cache/run_done events are in the ring too — the
            # "how did the end look" slice beside the full stream
            flight = self.telemetry.flight
            if flight is not None:
                try:
                    flight.dump(flight_path(self.cfg.workdir))
                except Exception as exc:
                    log.error("flight-ring dump failed: %s", exc)
            self.telemetry = None


class _JobAPIServer(http.server.ThreadingHTTPServer):
    """The loopback job API: thin JSON routing over the server object.

    Handler threads only ever call the server's locked methods; the
    dispatcher never runs here, so a slow client cannot stall a job.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, server: SegmentationServer) -> None:
        self.lt_server = server
        super().__init__(addr, _JobAPIHandler)

    def handle_error(self, request, client_address) -> None:
        import sys

        if isinstance(
            sys.exc_info()[1], (BrokenPipeError, ConnectionResetError)
        ):
            return
        super().handle_error(request, client_address)


class _JobAPIHandler(http.server.BaseHTTPRequestHandler):
    """Routes::

        POST /jobs              submit (JSON body → job snapshot | 429/400)
        GET  /jobs              every job's snapshot
        GET  /jobs/<id>         one job's snapshot
        POST /jobs/<id>/cancel  cancel (queued → terminal; running → event)
        GET  /healthz           liveness + queue/uptime/warm-program stats
        GET  /metrics           the lt_serve_* exposition
        GET  /metrics/exemplars histogram bucket → recent trace_id rings
        GET  /debug/flight      the flight ring's recent events
                                (?n=100, ?trace=<trace_id> filter)
        GET  /debug/stacks      all-thread tracebacks (sys._current_frames)
        GET  /debug/jobs        per-job live state incl. run progress
        GET  /debug/requests    recent terminal requests, slowest first
                                (trace_id + replica-side latency split)
        POST /debug/profile     on-demand bounded jax.profiler capture

    The ``/debug`` surface shares the job API's loopback-only bind (it
    reads process internals and triggers profiler captures) and is a
    404 wall when ``ServeConfig.debug_endpoints`` is off.  Handler
    threads only ever read locked snapshots; ``/debug/stacks`` in
    particular takes NO locks, so it answers even while the dispatcher
    is wedged — the question it exists for.
    """

    server: _JobAPIServer

    #: POST /debug/profile duration bound, seconds: long enough for any
    #: useful window, short enough that a typo'd duration cannot pin the
    #: process-global profiler for an hour
    MAX_PROFILE_S = 300.0

    def _send_json(self, status: int, payload) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if status == 429:
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - stdlib API name
        srv = self.server.lt_server
        path, _, query = self.path.partition("?")
        path = path.rstrip("/")
        if path.startswith("/debug"):
            if not srv.cfg.debug_endpoints:
                self.send_error(404)
                return
            if path == "/debug/flight":
                n = trace = None
                try:
                    from urllib.parse import parse_qs

                    params = parse_qs(query)
                    raw = params.get("n")
                    if raw:
                        n = max(1, int(raw[0]))
                    rawt = params.get("trace")
                    if rawt:
                        trace = rawt[0]
                except ValueError:
                    self._send_json(
                        400, {"error": "bad_request", "detail": "n must be int"}
                    )
                    return
                snap = srv.flight_snapshot(n, trace_id=trace)
                if snap is None:
                    self._send_json(
                        404,
                        {"error": "no flight ring (telemetry off or "
                                  "flight_ring_events=0)"},
                    )
                else:
                    self._send_json(200, snap)
            elif path == "/debug/stacks":
                from land_trendr_tpu.obs.flight import thread_stacks

                self._send_json(200, {"threads": thread_stacks()})
            elif path == "/debug/jobs":
                self._send_json(200, {"jobs": srv.debug_jobs()})
            elif path == "/debug/requests":
                self._send_json(200, {"requests": srv.debug_requests()})
            else:
                self.send_error(404)
            return
        if path == "/metrics/exemplars":
            if srv.telemetry is None:
                self.send_error(404)
                return
            self._send_json(
                200, {"exemplars": srv.telemetry.registry.exemplars()}
            )
            return
        if path == "/healthz":
            self._send_json(200, {"ok": True, **srv.stats()})
        elif path == "/metrics":
            if srv.telemetry is None:
                self.send_error(404)
                return
            body = srv.telemetry.registry.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/jobs":
            self._send_json(200, {"jobs": srv.jobs()})
        elif path.startswith("/jobs/"):
            snap = srv.job_status(path[len("/jobs/"):])
            if snap is None:
                self._send_json(404, {"error": "no such job"})
            else:
                self._send_json(200, snap)
        else:
            self.send_error(404)

    def do_POST(self) -> None:  # noqa: N802 - stdlib API name
        srv = self.server.lt_server
        path = self.path.split("?")[0].rstrip("/")
        if path == "/debug/profile":
            if not srv.cfg.debug_endpoints:
                self.send_error(404)
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(
                    400, {"error": "bad_request", "detail": str(e)}
                )
                return
            if not isinstance(payload, dict):
                self._send_json(
                    400,
                    {"error": "bad_request",
                     "detail": "body must be a JSON object"},
                )
                return
            duration_s = payload.get("duration_s", 1.0)
            # bool is an int subclass; `true` as a duration is a typo
            if isinstance(duration_s, bool) or not isinstance(
                duration_s, (int, float)
            ):
                self._send_json(
                    400,
                    {"error": "bad_request",
                     "detail": "duration_s must be a number"},
                )
                return
            duration_s = float(duration_s)
            if not (0 < duration_s <= self.MAX_PROFILE_S):
                self._send_json(
                    400,
                    {"error": "bad_request",
                     "detail": f"duration_s must be in (0, "
                               f"{self.MAX_PROFILE_S}]"},
                )
                return
            # synchronous by design: the capture is duration-bounded and
            # runs on THIS handler thread — the dispatcher (and its job)
            # keep running, which is exactly what the trace captures
            self._send_json(200, srv.capture_profile(duration_s))
            return
        if path == "/jobs":
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(
                    400, {"error": "bad_request", "detail": f"bad JSON: {e}"}
                )
                return
            try:
                snap = srv.submit(payload, source="http")
            except Rejection as e:
                self._send_json(
                    e.http_status,
                    {"error": e.reason, "detail": e.detail},
                )
                return
            self._send_json(200, snap)
        elif path.startswith("/jobs/") and path.endswith("/cancel"):
            job_id = path[len("/jobs/"):-len("/cancel")]
            snap = srv.cancel(job_id)
            if snap is None:
                self._send_json(404, {"error": "no such job"})
            else:
                self._send_json(200, snap)
        else:
            self.send_error(404)

    def log_message(self, *a) -> None:  # quiet: no per-request stderr
        pass
