"""Service-mode configuration: everything that defines one server process.

:class:`ServeConfig` is the serve-layer sibling of
:class:`~land_trendr_tpu.runtime.driver.RunConfig`: the one configuration
surface of ``lt serve``, projected to the ``serve`` CLI subcommand and to
README's ``## Serve configuration`` table (the LT004 coupling rule checks
all three, exactly like the RunConfig triangle).

Security posture: the job API is an **unauthenticated local control
surface** (submit arbitrary segmentation work, read job state, cancel),
so unlike the scrape-only ``/metrics`` endpoint it is loopback-ONLY —
``serve_host`` must name a loopback address and the config refuses
anything else at construction time.  Remote access goes through an
authenticated proxy or the filesystem drop-box, never a raw bind.
"""

from __future__ import annotations

import dataclasses

__all__ = ["LOOPBACK_HOSTS", "ServeConfig"]

#: the bind addresses the job API accepts — loopback spellings only (the
#: API is unauthenticated job submission; see the module docstring)
LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1")


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Everything that defines one ``lt serve`` server process."""

    #: server root: the server's own events/metrics stream, the default
    #: per-job ``jobs/<job_id>/{work,out}`` directories, and (with
    #: ``ingest_store_mb``) the shared persistent ingest store live here
    workdir: str = "lt_serve"
    #: loopback HTTP JSON API port (0 = ephemeral, reported at startup)
    serve_port: int = 0
    #: bind address for the job API — loopback only (``127.0.0.1``,
    #: ``localhost`` or ``::1``); see the module docstring
    serve_host: str = "127.0.0.1"
    #: admission control: a submission that would grow the queue past
    #: this depth is rejected with HTTP 429 (``job_rejected`` event,
    #: ``lt_serve_rejections_total``) instead of building unbounded
    #: backlog — the client owns the retry policy
    serve_queue_depth: int = 16
    #: admission control: per-tenant in-flight bound (queued + running
    #: jobs); a tenant at its cap gets 429 while other tenants' traffic
    #: proceeds — one hot tenant cannot monopolise the queue
    tenant_max_inflight: int = 4
    #: default per-job wall-clock bound, submit-accepted → terminal; a
    #: job that exceeds it is cancelled through the run's cancel event
    #: and reported ``stalled`` (the exit-4 analog — the stall
    #: watchdog's job-level mirror).  Jobs may override per request.
    #: ``None`` disables the default bound.
    job_timeout_s: float | None = None
    #: filesystem drop-box for batch submission: job-request JSON files
    #: appearing under this directory are claimed atomically (rename),
    #: submitted through the SAME admission control as HTTP, and answered
    #: with ``<name>.rejected.json`` / terminal ``<name>.result.json``
    #: sidecars.  ``None`` disables the scanner.
    dropbox_dir: str | None = None
    #: drop-box scan period, seconds
    dropbox_poll_s: float = 1.0
    #: drain this many jobs to a terminal state, then shut down cleanly —
    #: the bounded mode benches and tests drive; ``None`` serves forever
    max_jobs: int | None = None
    #: process-wide decoded-block cache budget (MiB) shared by every job
    #: (the server owns the :mod:`land_trendr_tpu.io.blockcache`
    #: configuration; per-job RunConfig cache knobs are overridden)
    feed_cache_mb: int = 256
    #: shared feed-decode threads (the blockcache knob): 0 = auto
    decode_workers: int = 0
    #: shared persistent ingest store budget (MiB): decoded blocks from
    #: EVERY job spill to one store under the server workdir, so a warm
    #: job over already-ingested stacks skips TIFF decode entirely —
    #: "ingest once, serve many" across requests.  0 = off.
    ingest_store_mb: int = 0
    #: store directory override (default ``<workdir>/ingest_store``)
    ingest_store_dir: str | None = None
    #: shared tuning store (:mod:`land_trendr_tpu.tune`, ``lt tune``'s
    #: output): every job whose RunConfig carries ``"auto"`` knob
    #: sentinels (and no store of its own) resolves them through this
    #: store, so the whole replica — and a fleet of replicas pointed at
    #: one directory — runs tuned.  Per-job explicit knobs always win;
    #: ``None`` leaves ``"auto"`` resolving to the hardcoded defaults.
    tune_store_dir: str | None = None
    #: server + per-job telemetry: the server writes its own
    #: ``events.jsonl`` scope (job lifecycle, admission, program-cache
    #: aggregate) and ``lt_serve_*`` metrics under ``workdir``; each
    #: job's run writes its own scope under the job workdir with the
    #: job_id threaded onto every event
    telemetry: bool = True
    #: with ``telemetry``: serve the server registry's live ``/metrics``
    #: on this port (0 = ephemeral).  ``None`` = no standalone metrics
    #: server (the job API serves GET /metrics regardless).
    metrics_port: int | None = None
    #: bind address for ``metrics_port`` (the scrape endpoint may be
    #: non-loopback — it is read-only, unlike the job API)
    metrics_host: str = ""
    #: ``metrics.prom`` refresh period, seconds
    metrics_interval_s: float = 5.0
    #: deterministic fault injection for soak runs: the server arms ONE
    #: process-wide plan shared by every job (``serve.submit`` /
    #: ``serve.job`` seams plus all the pipeline seams); production
    #: servers leave this unset
    fault_schedule: str | None = None
    #: the live ``/debug`` surface on the job API (``/debug/flight``,
    #: ``/debug/stacks``, ``/debug/jobs``, ``POST /debug/profile``) —
    #: loopback-only like the rest of the API (it reads process
    #: internals and triggers profiler captures).  ``False`` turns every
    #: ``/debug`` route into a 404.
    debug_endpoints: bool = True
    #: flight-recorder ring capacity, events: with ``telemetry``, a
    #: bounded in-memory ring mirrors every server AND job event (the
    #: ``/debug/flight`` window, dumped to ``<workdir>/flight.jsonl`` at
    #: shutdown) and a sampler thread emits periodic ``flight_sample``
    #: resource events.  ``0`` disables the ring + sampler.
    flight_ring_events: int = 2048
    #: flight resource-sampler period, seconds
    sampler_interval_s: float = 5.0
    #: request-tracing recency bound: how many recent TERMINAL requests
    #: (trace id, latency split, status) ``GET /debug/requests`` serves,
    #: slowest-first — the human half of the exemplar loop (the
    #: ``/metrics/exemplars`` JSON is the machine half).  0 disables
    #: the ring (the endpoint then answers an empty list).
    request_ring: int = 64
    #: fleet telemetry plane (:mod:`land_trendr_tpu.obs` publish /
    #: aggregate / history / alerts): with ``telemetry``, the server
    #: periodically (1) snapshots its registry + queue/SLO state into
    #: an atomic ``<telemetry_dir>/<host>.<pid>.snap.json``, (2) folds
    #: EVERY snapshot under that shared directory into one pod view —
    #: sibling replicas and standalone runs pointed at the same dir
    #: included — (3) appends the fold to the on-disk history ring
    #: under ``<workdir>/history``, and (4) evaluates the alert rules
    #: over that history (``alert`` events, ``lt_alerts_*`` metrics,
    #: active alerts on ``/healthz`` and ``lt top``).
    publish: bool = False
    #: fleet beat period, seconds (snapshot refresh + fold + alert
    #: evaluation)
    publish_interval_s: float = 5.0
    #: shared telemetry directory override (default
    #: ``<workdir>/telemetry``) — point N replicas at one directory to
    #: aggregate the fleet
    telemetry_dir: str | None = None
    #: alert-rules file (JSON, :func:`land_trendr_tpu.obs.alerts.
    #: load_rules`) — ``None`` uses the built-in defaults (host
    #: staleness/absence + SLO burn).  Parsed at config time: a typo'd
    #: rule is a startup error, not a dead rule discovered after the
    #: incident.
    alert_rules: str | None = None
    #: cross-job continuous batching (:mod:`land_trendr_tpu.serve.
    #: batching`): coalesce queued same-affinity jobs behind one shared
    #: device launch — compute once, demux byte-identical artifacts to
    #: every member.  ``True``/``False`` force it; ``"auto"`` resolves
    #: through the replica's tuning store (``tune_store_dir``) at batch
    #: time, defaulting ON (batching changes packing, never bytes or
    #: fairness ordering).
    batch: bool | str = "auto"
    #: how long the dispatcher holds the batch window open (milliseconds)
    #: for same-affinity stragglers to join the popped leader before
    #: launching — the window closes EARLY the moment a non-matching job
    #: reaches the queue front (batching must never delay the fairness
    #: order).  0 batches only what is already queued.
    batch_window_ms: float = 50.0
    #: batch size bound, total coalesced tiles (member jobs × tiles per
    #: job); members past the bound run solo in their normal queue turn.
    #: 0 = unbounded.
    batch_max_tiles: int = 0

    def __post_init__(self) -> None:
        if not (0 <= self.serve_port <= 65535):
            raise ValueError(
                f"serve_port={self.serve_port} outside 0..65535"
            )
        if self.serve_host not in LOOPBACK_HOSTS:
            raise ValueError(
                f"serve_host={self.serve_host!r} is not a loopback "
                f"address {LOOPBACK_HOSTS}: the job API is an "
                "unauthenticated control surface and never binds a "
                "routable interface (front it with an authenticated "
                "proxy, or use the drop-box)"
            )
        if self.serve_queue_depth < 1:
            raise ValueError(
                f"serve_queue_depth={self.serve_queue_depth} must be >= 1"
            )
        if self.tenant_max_inflight < 1:
            raise ValueError(
                f"tenant_max_inflight={self.tenant_max_inflight} must be "
                ">= 1"
            )
        if self.job_timeout_s is not None and self.job_timeout_s <= 0:
            raise ValueError(
                f"job_timeout_s={self.job_timeout_s} must be > 0 (or "
                "None for no default bound)"
            )
        if self.dropbox_poll_s <= 0:
            raise ValueError(
                f"dropbox_poll_s={self.dropbox_poll_s} must be > 0"
            )
        if self.max_jobs is not None and self.max_jobs < 1:
            raise ValueError(
                f"max_jobs={self.max_jobs} must be >= 1 (or None to "
                "serve forever)"
            )
        if self.feed_cache_mb < 0:
            raise ValueError(
                f"feed_cache_mb={self.feed_cache_mb} must be >= 0 (0 = off)"
            )
        if self.decode_workers < 0:
            raise ValueError(
                f"decode_workers={self.decode_workers} must be >= 0 "
                "(0 = auto)"
            )
        if self.ingest_store_mb < 0:
            raise ValueError(
                f"ingest_store_mb={self.ingest_store_mb} must be >= 0 "
                "(0 = off)"
            )
        if self.ingest_store_dir is not None and not self.ingest_store_mb:
            raise ValueError(
                "ingest_store_dir requires ingest_store_mb > 0 (there is "
                "no store to place without a budget)"
            )
        if self.metrics_port is not None:
            if not self.telemetry:
                raise ValueError(
                    "metrics_port requires telemetry=True (the registry "
                    "the endpoint serves only exists on telemetry runs)"
                )
            if not (0 <= self.metrics_port <= 65535):
                raise ValueError(
                    f"metrics_port={self.metrics_port} outside 0..65535"
                )
        elif self.metrics_host:
            raise ValueError(
                "metrics_host requires metrics_port (there is no server "
                "to bind without a port)"
            )
        if self.metrics_interval_s <= 0:
            raise ValueError(
                f"metrics_interval_s={self.metrics_interval_s} must be > 0"
            )
        if self.flight_ring_events < 0 or self.flight_ring_events == 1:
            raise ValueError(
                f"flight_ring_events={self.flight_ring_events} must be 0 "
                "(off) or >= 2 (a useful ring holds at least a run_start "
                "and one event)"
            )
        if self.sampler_interval_s <= 0:
            raise ValueError(
                f"sampler_interval_s={self.sampler_interval_s} must be > 0"
            )
        if self.request_ring < 0:
            raise ValueError(
                f"request_ring={self.request_ring} must be >= 0 (0 = off)"
            )
        if self.publish and not self.telemetry:
            raise ValueError(
                "publish requires telemetry=True (the fleet snapshot is "
                "a dump of the telemetry registry; there is nothing to "
                "publish without one)"
            )
        if self.publish_interval_s <= 0:
            raise ValueError(
                f"publish_interval_s={self.publish_interval_s} must be > 0"
            )
        if self.telemetry_dir is not None and not self.publish:
            raise ValueError(
                "telemetry_dir requires publish=True (there is no "
                "snapshot to place without a publisher)"
            )
        if self.alert_rules is not None:
            if not self.publish:
                raise ValueError(
                    "alert_rules requires publish=True (rules are "
                    "evaluated by the fleet loop)"
                )
            # parse NOW: a typo'd rule is a startup error, like
            # fault_schedule below
            from land_trendr_tpu.obs.alerts import load_rules

            try:
                load_rules(self.alert_rules)
            except OSError as e:
                raise ValueError(
                    f"alert_rules file unreadable: {e}"
                ) from None
        if not (
            self.batch is True or self.batch is False or self.batch == "auto"
        ):
            raise ValueError(
                f"batch={self.batch!r} must be True, False or 'auto' "
                "(tuning-store resolution)"
            )
        if self.batch_window_ms < 0:
            raise ValueError(
                f"batch_window_ms={self.batch_window_ms} must be >= 0 "
                "(0 = batch only what is already queued)"
            )
        if self.batch_max_tiles < 0:
            raise ValueError(
                f"batch_max_tiles={self.batch_max_tiles} must be >= 0 "
                "(0 = unbounded)"
            )
        if self.fault_schedule is not None:
            # parse NOW: a typo'd seam is a config error at startup, not
            # a dead injection discovered after the soak run (the same
            # contract as RunConfig.fault_schedule)
            from land_trendr_tpu.runtime import faults

            faults.parse_schedule(self.fault_schedule)
