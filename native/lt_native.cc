// lt_native — native raster-codec hot loops for land_trendr_tpu.
//
// The reference's raster layer leans on GDAL's C++ core under Python
// bindings (SURVEY.md §2 L1, §3 "Native components": the only native code
// in the reference stack is third-party GDAL + the Hadoop JVM).  This
// library is the rebuild's equivalent native layer: the GeoTIFF codec's
// per-block hot loops — inflate + horizontal-predictor undo on decode,
// predictor apply + deflate on encode — fused in C++ and threaded across
// blocks, behind a C ABI consumed via ctypes (land_trendr_tpu/io/native.py).
// The pure-NumPy path in io/geotiff.py remains the behavioural reference
// and the fallback when this library isn't built.
//
// Threading: blocks are independent (same unit of work the TIFF format
// defines), pulled off an atomic counter by a small thread pool.  On the
// CONUS-scale ingest path (SURVEY.md §7 hard-part 4) decode bandwidth is
// what keeps the host ahead of the TPU's ~2.4 GB/s/chip appetite.
//
// Build: make -C native   (g++ -O3 -shared -fPIC, links zlib + pthread)

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

constexpr int kCompNone = 1;
constexpr int kCompDeflateAdobe = 8;
constexpr int kCompDeflateOld = 32946;

constexpr int kOk = 0;
constexpr int kErrInflate = -1;
constexpr int kErrDeflate = -2;
constexpr int kErrBadArg = -3;
constexpr int kErrShortData = -4;

// Inflate `src` into exactly `dst_len` bytes of `dst`.  TIFF deflate blocks
// are zlib streams in practice, but raw-deflate files exist (old code 32946
// writers) — retry headerless on a header error, mirroring the Python
// codec's zlib.decompress fallback.  A stream that ends short of `dst_len`
// is an error (truncated block): the caller passes the exact expected size,
// including legally-short last strips, so partial fill always means
// corruption — matching the NumPy path's frombuffer failure.  Extra stream
// data beyond `dst_len` is tolerated like NumPy's frombuffer(count=...).
int inflate_block(const uint8_t* src, size_t src_len, uint8_t* dst,
                  size_t dst_len) {
  for (int window : {MAX_WBITS, -MAX_WBITS}) {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, window) != Z_OK) return kErrInflate;
    zs.next_in = const_cast<Bytef*>(src);
    zs.avail_in = static_cast<uInt>(src_len);
    zs.next_out = dst;
    zs.avail_out = static_cast<uInt>(dst_len);
    int rc = inflate(&zs, Z_FINISH);
    inflateEnd(&zs);
    if ((rc == Z_STREAM_END || rc == Z_OK || rc == Z_BUF_ERROR) &&
        zs.avail_out == 0)
      return kOk;
    if (rc == Z_STREAM_END) return kErrShortData;  // truncated block
    // only fall through to raw-deflate on an immediate header rejection
    if (window == MAX_WBITS && rc == Z_DATA_ERROR && zs.total_in < 2) continue;
    return kErrInflate;
  }
  return kErrInflate;
}

// Undo TIFF predictor 2 (horizontal differencing): within each row, each
// pixel's sample accumulates the previous pixel's same sample.  Arithmetic
// is modular in the sample width — unsigned of matching width reproduces
// NumPy's wrapping cumsum for both signed and unsigned dtypes.
template <typename T>
void unpredict_rows(uint8_t* data, int rows, int width, int spp) {
  for (int r = 0; r < rows; ++r) {
    T* row = reinterpret_cast<T*>(data) + static_cast<size_t>(r) * width * spp;
    for (int x = 1; x < width; ++x)
      for (int s = 0; s < spp; ++s)
        row[x * spp + s] = static_cast<T>(row[x * spp + s] +
                                          row[(x - 1) * spp + s]);
  }
}

template <typename T>
void predict_rows(uint8_t* data, int rows, int width, int spp) {
  for (int r = 0; r < rows; ++r) {
    T* row = reinterpret_cast<T*>(data) + static_cast<size_t>(r) * width * spp;
    for (int x = width - 1; x >= 1; --x)
      for (int s = 0; s < spp; ++s)
        row[x * spp + s] = static_cast<T>(row[x * spp + s] -
                                          row[(x - 1) * spp + s]);
  }
}

void apply_predictor(uint8_t* data, int rows, int width, int spp,
                     int elem_size, bool undo) {
  switch (elem_size) {
    case 1:
      undo ? unpredict_rows<uint8_t>(data, rows, width, spp)
           : predict_rows<uint8_t>(data, rows, width, spp);
      break;
    case 2:
      undo ? unpredict_rows<uint16_t>(data, rows, width, spp)
           : predict_rows<uint16_t>(data, rows, width, spp);
      break;
    case 4:
      undo ? unpredict_rows<uint32_t>(data, rows, width, spp)
           : predict_rows<uint32_t>(data, rows, width, spp);
      break;
  }
}

int pick_threads(int n_blocks, int n_threads) {
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 1;
  }
  if (n_threads > n_blocks) n_threads = n_blocks;
  return n_threads < 1 ? 1 : n_threads;
}

template <typename Fn>
int run_blocks(int n_blocks, int n_threads, Fn&& per_block) {
  n_threads = pick_threads(n_blocks, n_threads);
  std::atomic<int> next{0};
  std::atomic<int> status{kOk};
  auto worker = [&]() {
    int i;
    while ((i = next.fetch_add(1)) < n_blocks) {
      if (status.load(std::memory_order_relaxed) != kOk) return;
      int rc = per_block(i);
      if (rc != kOk) status.store(rc, std::memory_order_relaxed);
    }
  };
  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return status.load();
}

}  // namespace

extern "C" {

// ABI version — bump on any signature change; the ctypes binding checks it.
int lt_native_abi_version() { return 2; }

// Decode n_blocks TIFF blocks from a memory-mapped/loaded file image.
//
//   file_data/file_len  whole file bytes
//   offsets/counts      per-block byte ranges (uint64, from the IFD)
//   block_rows          per-block REAL row count (uint64; < `rows` only for
//                       a legally-short last strip) — the decoded payload
//                       must cover exactly block_rows*width*spp samples or
//                       the block is treated as corrupt
//   compression         TIFF tag 259 value (1, 8, or 32946)
//   predictor           TIFF tag 317 value (1 or 2)
//   rows/width/spp      decoded block slot geometry (rows*width*spp samples)
//   elem_size           bytes per sample (1, 2, 4, or 8)
//   out                 n_blocks contiguous decoded slots, caller-allocated
//   n_threads           0 = hardware concurrency
//
// Returns 0 or a negative error code.  Little-endian samples only (the
// Python layer routes big-endian files to the NumPy path).
int lt_decode_blocks(const uint8_t* file_data, uint64_t file_len,
                     const uint64_t* offsets, const uint64_t* counts,
                     const uint64_t* block_rows, int n_blocks,
                     int compression, int predictor, int rows, int width,
                     int spp, int elem_size, uint8_t* out, int n_threads) {
  if (n_blocks < 0 || rows <= 0 || width <= 0 || spp <= 0) return kErrBadArg;
  if (elem_size != 1 && elem_size != 2 && elem_size != 4 && elem_size != 8)
    return kErrBadArg;
  if (compression != kCompNone && compression != kCompDeflateAdobe &&
      compression != kCompDeflateOld)
    return kErrBadArg;
  if (predictor == 2 && elem_size == 8) return kErrBadArg;  // floats only
  const size_t row_bytes = static_cast<size_t>(width) * spp * elem_size;
  const size_t slot_bytes = static_cast<size_t>(rows) * row_bytes;

  return run_blocks(n_blocks, n_threads, [&](int i) -> int {
    // Overflow-safe: offsets[i] + counts[i] can wrap in uint64 for corrupt
    // or malicious IFD entries, bypassing a naive sum check.
    if (offsets[i] > file_len || counts[i] > file_len - offsets[i])
      return kErrShortData;
    if (block_rows[i] > static_cast<uint64_t>(rows)) return kErrBadArg;
    const size_t want = block_rows[i] * row_bytes;
    const uint8_t* src = file_data + offsets[i];
    uint8_t* dst = out + static_cast<size_t>(i) * slot_bytes;
    if (compression == kCompNone) {
      if (counts[i] < want) return kErrShortData;
      std::memcpy(dst, src, want);
    } else {
      int rc = inflate_block(src, counts[i], dst, want);
      if (rc != kOk) return rc;
    }
    if (predictor == 2)
      apply_predictor(dst, static_cast<int>(block_rows[i]), width, spp,
                      elem_size, /*undo=*/true);
    return kOk;
  });
}

// Encode n_blocks equal-geometry blocks with optional predictor + deflate.
//
//   blocks       n_blocks contiguous input blocks (modified in place when
//                predictor=2 — pass a scratch copy)
//   out          caller-allocated, n_blocks * bound bytes
//   bound        per-block output capacity (>= lt_deflate_bound(block_bytes))
//   out_sizes    per-block compressed byte counts (written)
//   level        zlib level (6 matches the Python writer)
int lt_encode_blocks(uint8_t* blocks, int n_blocks, int predictor, int rows,
                     int width, int spp, int elem_size, uint8_t* out,
                     uint64_t bound, uint64_t* out_sizes, int level,
                     int n_threads) {
  if (n_blocks < 0 || rows <= 0 || width <= 0 || spp <= 0) return kErrBadArg;
  if (elem_size != 1 && elem_size != 2 && elem_size != 4 && elem_size != 8)
    return kErrBadArg;
  if (predictor == 2 && elem_size == 8) return kErrBadArg;
  const size_t block_bytes =
      static_cast<size_t>(rows) * width * spp * elem_size;
  if (bound < compressBound(static_cast<uLong>(block_bytes))) return kErrBadArg;

  return run_blocks(n_blocks, n_threads, [&](int i) -> int {
    uint8_t* src = blocks + static_cast<size_t>(i) * block_bytes;
    if (predictor == 2)
      apply_predictor(src, rows, width, spp, elem_size, /*undo=*/false);
    uLongf dst_len = static_cast<uLongf>(bound);
    int rc = compress2(out + static_cast<size_t>(i) * bound, &dst_len, src,
                       static_cast<uLong>(block_bytes), level);
    if (rc != Z_OK) return kErrDeflate;
    out_sizes[i] = dst_len;
    return kOk;
  });
}

uint64_t lt_deflate_bound(uint64_t n) {
  return compressBound(static_cast<uLong>(n));
}

}  // extern "C"
