// lt_native — native raster-codec hot loops for land_trendr_tpu.
//
// The reference's raster layer leans on GDAL's C++ core under Python
// bindings (SURVEY.md §2 L1, §3 "Native components": the only native code
// in the reference stack is third-party GDAL + the Hadoop JVM).  This
// library is the rebuild's equivalent native layer: the GeoTIFF codec's
// per-block hot loops — inflate + horizontal-predictor undo on decode,
// predictor apply + deflate on encode — fused in C++ and threaded across
// blocks, behind a C ABI consumed via ctypes (land_trendr_tpu/io/native.py).
// The pure-NumPy path in io/geotiff.py remains the behavioural reference
// and the fallback when this library isn't built.
//
// Threading: blocks are independent (same unit of work the TIFF format
// defines), pulled off an atomic counter by a small thread pool.  On the
// CONUS-scale ingest path (SURVEY.md §7 hard-part 4) decode bandwidth is
// what keeps the host ahead of the TPU's ~2.4 GB/s/chip appetite.
//
// Build: make -C native   (g++ -O3 -shared -fPIC, links zlib + pthread)

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <unordered_map>
#include <vector>

#include <zlib.h>

namespace {

constexpr int kCompNone = 1;
constexpr int kCompLzw = 5;
constexpr int kCompDeflateAdobe = 8;
constexpr int kCompDeflateOld = 32946;

constexpr int kOk = 0;
constexpr int kErrInflate = -1;
constexpr int kErrDeflate = -2;
constexpr int kErrBadArg = -3;
constexpr int kErrShortData = -4;
constexpr int kErrLzw = -5;

// Inflate `src` into exactly `dst_len` bytes of `dst`.  TIFF deflate blocks
// are zlib streams in practice, but raw-deflate files exist (old code 32946
// writers) — retry headerless on a header error, mirroring the Python
// codec's zlib.decompress fallback.  A stream that ends short of `dst_len`
// is an error (truncated block): the caller passes the exact expected size,
// including legally-short last strips, so partial fill always means
// corruption — matching the NumPy path's frombuffer failure.  Extra stream
// data beyond `dst_len` is tolerated like NumPy's frombuffer(count=...).
int inflate_block(const uint8_t* src, size_t src_len, uint8_t* dst,
                  size_t dst_len) {
  for (int window : {MAX_WBITS, -MAX_WBITS}) {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, window) != Z_OK) return kErrInflate;
    zs.next_in = const_cast<Bytef*>(src);
    zs.avail_in = static_cast<uInt>(src_len);
    zs.next_out = dst;
    zs.avail_out = static_cast<uInt>(dst_len);
    int rc = inflate(&zs, Z_FINISH);
    inflateEnd(&zs);
    if ((rc == Z_STREAM_END || rc == Z_OK || rc == Z_BUF_ERROR) &&
        zs.avail_out == 0)
      return kOk;
    if (rc == Z_STREAM_END) return kErrShortData;  // truncated block
    // only fall through to raw-deflate on an immediate header rejection
    if (window == MAX_WBITS && rc == Z_DATA_ERROR && zs.total_in < 2) continue;
    return kErrInflate;
  }
  return kErrInflate;
}

// TIFF 6.0 LZW (compression 5): MSB-first bit packing, ClearCode=256,
// EOI=257, code width 9→12 bits with the spec's "early change" (width bumps
// when the next free code reaches 511/1023/2047).  Mirrors the Python
// reference decoder in io/geotiff.py::_lzw_decode byte for byte; like
// inflate_block, a stream that fills less than dst_len is corrupt and
// extra decoded bytes beyond dst_len are tolerated (NumPy frombuffer
// count=... semantics).
int lzw_decode(const uint8_t* src, size_t src_len, uint8_t* dst,
               size_t dst_len) {
  constexpr int kClear = 256, kEoi = 257, kTable = 1 << 12;
  static_assert(kTable == 4096, "TIFF LZW is 12-bit");
  uint16_t prefix[kTable];
  uint8_t suffix[kTable];
  uint8_t firstb[kTable];
  uint16_t length[kTable];
  for (int c = 0; c < 256; ++c) {
    prefix[c] = 0;
    suffix[c] = static_cast<uint8_t>(c);
    firstb[c] = static_cast<uint8_t>(c);
    length[c] = 1;
  }
  length[kClear] = length[kEoi] = 0;

  size_t out = 0;
  size_t bitpos = 0;
  const size_t total_bits = src_len * 8;
  int code_bits = 9;
  int next_code = 258;
  int prev = -1;

  auto read_code = [&]() -> int {
    if (bitpos + static_cast<size_t>(code_bits) > total_bits) return kEoi;
    size_t byte0 = bitpos >> 3;
    uint32_t chunk = 0;
    for (size_t k = 0; k < 4; ++k)
      chunk = (chunk << 8) |
              (byte0 + k < src_len ? src[byte0 + k] : 0u);
    int shift = 32 - code_bits - static_cast<int>(bitpos & 7);
    bitpos += code_bits;
    return static_cast<int>((chunk >> shift) & ((1u << code_bits) - 1));
  };
  // Sequences decode last-byte-first; stage in a stack buffer, then copy
  // the prefix that still fits (frombuffer count=... tolerance).
  uint8_t tmp[kTable];
  auto emit = [&](int code) {
    int len = length[code];
    int c = code, k = len;
    while (k > 1) {
      tmp[--k] = suffix[c];
      c = prefix[c];
    }
    tmp[0] = suffix[c];
    // `out` keeps counting past dst_len (overlong streams are tolerated,
    // final fill is checked at return); only the copy is clamped, and only
    // while there is room — out may already be past the end here.
    if (out < dst_len) {
      size_t n = static_cast<size_t>(len);
      if (out + n > dst_len) n = dst_len - out;
      std::memcpy(dst + out, tmp, n);
    }
    out += static_cast<size_t>(len);
  };

  while (true) {
    int code = read_code();
    if (code == kEoi) break;
    if (code == kClear) {
      code_bits = 9;
      next_code = 258;
      do {  // libtiff tolerates consecutive Clear codes
        code = read_code();
      } while (code == kClear);
      if (code == kEoi) break;
      if (code >= 256) return kErrLzw;  // first post-clear code is a literal
      if (out < dst_len) dst[out] = static_cast<uint8_t>(code);
      ++out;
      prev = code;
      continue;
    }
    if (prev < 0 || next_code >= kTable) return kErrLzw;  // no leading clear / table overflow
    if (code < next_code) {
      // existing entry; new table slot = prev_seq + first byte of code_seq
      prefix[next_code] = static_cast<uint16_t>(prev);
      suffix[next_code] = firstb[code];
      firstb[next_code] = firstb[prev];
      length[next_code] = static_cast<uint16_t>(length[prev] + 1);
      emit(code);
    } else if (code == next_code) {
      // KwKwK: entry = prev_seq + first byte of prev_seq, emitted itself
      prefix[next_code] = static_cast<uint16_t>(prev);
      suffix[next_code] = firstb[prev];
      firstb[next_code] = firstb[prev];
      length[next_code] = static_cast<uint16_t>(length[prev] + 1);
      emit(code);
    } else {
      return kErrLzw;  // code beyond table: corrupt stream
    }
    ++next_code;
    if (next_code == (1 << code_bits) - 1 && code_bits < 12) ++code_bits;
    prev = code;
  }
  return out >= dst_len ? kOk : kErrShortData;
}

// TIFF 6.0 LZW encoder — mirrors geotiff._lzw_encode decision for decision
// (greedy longest-match, early-change width bumps at (1<<bits) on the
// encoder side, the terminal-code bump before EOI, Clear+reset at 4094),
// so outputs are byte-identical to the Python reference (tests assert it).
// The dictionary is (prefix_code<<8 | byte) → code in a hash map — one
// probe per input byte, O(n) overall.
int lzw_encode(const uint8_t* src, size_t n, uint8_t* dst, size_t cap,
               uint64_t* out_len) {
  constexpr int kClear = 256, kEoi = 257;
  uint32_t buf = 0;
  int nbits = 0;
  int code_bits = 9;
  size_t out = 0;
  auto emit = [&](int code) -> bool {
    buf = (buf << code_bits) | static_cast<uint32_t>(code);
    nbits += code_bits;
    while (nbits >= 8) {
      nbits -= 8;
      if (out >= cap) return false;
      dst[out++] = static_cast<uint8_t>((buf >> nbits) & 0xFF);
    }
    buf &= (1u << nbits) - 1;
    return true;
  };
  std::unordered_map<uint32_t, int> table;
  table.reserve(4096);
  int next_code = 258;
  if (!emit(kClear)) return kErrLzw;
  int prev = -1;
  for (size_t i = 0; i < n; ++i) {
    const int b = src[i];
    if (prev < 0) {
      prev = b;
      continue;
    }
    const uint32_t key = (static_cast<uint32_t>(prev) << 8) | b;
    auto it = table.find(key);
    if (it != table.end()) {
      prev = it->second;
      continue;
    }
    if (!emit(prev)) return kErrLzw;
    table.emplace(key, next_code);
    ++next_code;
    prev = b;
    if (next_code == (1 << code_bits) && code_bits < 12) {
      ++code_bits;  // decoder lags one add; it bumps at (1<<bits)-1
    } else if (next_code >= 4094) {
      if (!emit(kClear)) return kErrLzw;
      table.clear();
      next_code = 258;
      code_bits = 9;
    }
  }
  if (prev >= 0) {
    if (!emit(prev)) return kErrLzw;
    // the decoder's add for this final code can trigger its bump — EOI
    // must be written at the width it will be read with
    if (next_code == (1 << code_bits) - 1 && code_bits < 12) ++code_bits;
  }
  if (!emit(kEoi)) return kErrLzw;
  if (nbits) {
    if (out >= cap) return kErrLzw;
    dst[out++] = static_cast<uint8_t>((buf << (8 - nbits)) & 0xFF);
  }
  *out_len = out;
  return kOk;
}

// Undo TIFF predictor 2 (horizontal differencing): within each row, each
// pixel's sample accumulates the previous pixel's same sample.  Arithmetic
// is modular in the sample width — unsigned of matching width reproduces
// NumPy's wrapping cumsum for both signed and unsigned dtypes.
template <typename T>
void unpredict_rows(uint8_t* data, int rows, int width, int spp) {
  for (int r = 0; r < rows; ++r) {
    T* row = reinterpret_cast<T*>(data) + static_cast<size_t>(r) * width * spp;
    for (int x = 1; x < width; ++x)
      for (int s = 0; s < spp; ++s)
        row[x * spp + s] = static_cast<T>(row[x * spp + s] +
                                          row[(x - 1) * spp + s]);
  }
}

template <typename T>
void predict_rows(uint8_t* data, int rows, int width, int spp) {
  for (int r = 0; r < rows; ++r) {
    T* row = reinterpret_cast<T*>(data) + static_cast<size_t>(r) * width * spp;
    for (int x = width - 1; x >= 1; --x)
      for (int s = 0; s < spp; ++s)
        row[x * spp + s] = static_cast<T>(row[x * spp + s] -
                                          row[(x - 1) * spp + s]);
  }
}

void apply_predictor(uint8_t* data, int rows, int width, int spp,
                     int elem_size, bool undo) {
  switch (elem_size) {
    case 1:
      undo ? unpredict_rows<uint8_t>(data, rows, width, spp)
           : predict_rows<uint8_t>(data, rows, width, spp);
      break;
    case 2:
      undo ? unpredict_rows<uint16_t>(data, rows, width, spp)
           : predict_rows<uint16_t>(data, rows, width, spp);
      break;
    case 4:
      undo ? unpredict_rows<uint32_t>(data, rows, width, spp)
           : predict_rows<uint32_t>(data, rows, width, spp);
      break;
  }
}

// see lt_gather_tile: one thread's row block of the feed-layout transpose
template <typename T>
void gather_rows(const uint8_t* src, int ny, int height, int width, int y0,
                 int x0, int w, uint8_t* dst, int y_begin, int y_end) {
  const T* s = reinterpret_cast<const T*>(src);
  T* d = reinterpret_cast<T*>(dst);
  const size_t plane = static_cast<size_t>(height) * width;
  for (int y = y_begin; y < y_end; ++y) {
    const size_t row_base = static_cast<size_t>(y0 + y) * width + x0;
    T* drow = d + static_cast<size_t>(y) * w * ny;
    for (int x = 0; x < w; ++x) {
      const T* col = s + row_base + x;
      T* dpx = drow + static_cast<size_t>(x) * ny;
      for (int n = 0; n < ny; ++n) dpx[n] = col[static_cast<size_t>(n) * plane];
    }
  }
}

int pick_threads(int n_blocks, int n_threads) {
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 1;
  }
  if (n_threads > n_blocks) n_threads = n_blocks;
  return n_threads < 1 ? 1 : n_threads;
}

template <typename Fn>
int run_blocks(int n_blocks, int n_threads, Fn&& per_block) {
  n_threads = pick_threads(n_blocks, n_threads);
  std::atomic<int> next{0};
  std::atomic<int> status{kOk};
  auto worker = [&]() {
    int i;
    while ((i = next.fetch_add(1)) < n_blocks) {
      if (status.load(std::memory_order_relaxed) != kOk) return;
      int rc = per_block(i);
      if (rc != kOk) status.store(rc, std::memory_order_relaxed);
    }
  };
  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return status.load();
}

}  // namespace

extern "C" {

// ABI version — bump on any signature or behaviour-surface change (v3 added
// LZW decode; v4 added a compression arg to lt_encode_blocks for LZW
// encode; v5 adds lt_gather_tile; v6 adds lt_write_store_zip); the ctypes
// binding checks it.
int lt_native_abi_version() { return 6; }

// Gather one tile window into device-feed layout: a (NY, H, W) cube's
// window (y0, x0, h, w) becomes the (h*w, NY) array the kernel wants —
// the host feed path's hot transpose (SURVEY.md §7 hard-part 4:
// ~2.4 GB/s/chip at the 10M px/s target; NumPy's strided-transpose copy
// measures ~1 GB/s/core).  Threaded over output row blocks: writes are
// fully sequential, reads are NY interleaved sequential streams the
// prefetcher handles well.
int lt_gather_tile(const uint8_t* src, int ny, int height, int width, int y0,
                   int x0, int h, int w, int elem_size, uint8_t* dst,
                   int n_threads) {
  if (ny <= 0 || height <= 0 || width <= 0 || h <= 0 || w <= 0)
    return kErrBadArg;
  if (y0 < 0 || x0 < 0 || y0 + h > height || x0 + w > width) return kErrBadArg;
  if (elem_size != 1 && elem_size != 2 && elem_size != 4 && elem_size != 8)
    return kErrBadArg;
  // split rows into blocks, one work item each
  const int block = 16;
  const int n_blocks = (h + block - 1) / block;
  return run_blocks(n_blocks, n_threads, [&](int i) -> int {
    const int yb = i * block;
    const int ye = yb + block < h ? yb + block : h;
    switch (elem_size) {
      case 1: gather_rows<uint8_t>(src, ny, height, width, y0, x0, w, dst, yb, ye); break;
      case 2: gather_rows<uint16_t>(src, ny, height, width, y0, x0, w, dst, yb, ye); break;
      case 4: gather_rows<uint32_t>(src, ny, height, width, y0, x0, w, dst, yb, ye); break;
      default: gather_rows<uint64_t>(src, ny, height, width, y0, x0, w, dst, yb, ye); break;
    }
    return kOk;
  });
}

// Decode n_blocks TIFF blocks from a memory-mapped/loaded file image.
//
//   file_data/file_len  whole file bytes
//   offsets/counts      per-block byte ranges (uint64, from the IFD)
//   block_rows          per-block REAL row count (uint64; < `rows` only for
//                       a legally-short last strip) — the decoded payload
//                       must cover exactly block_rows*width*spp samples or
//                       the block is treated as corrupt
//   compression         TIFF tag 259 value (1, 8, or 32946)
//   predictor           TIFF tag 317 value (1 or 2)
//   rows/width/spp      decoded block slot geometry (rows*width*spp samples)
//   elem_size           bytes per sample (1, 2, 4, or 8)
//   out                 n_blocks contiguous decoded slots, caller-allocated
//   n_threads           0 = hardware concurrency
//
// Returns 0 or a negative error code.  Little-endian samples only (the
// Python layer routes big-endian files to the NumPy path).
int lt_decode_blocks(const uint8_t* file_data, uint64_t file_len,
                     const uint64_t* offsets, const uint64_t* counts,
                     const uint64_t* block_rows, int n_blocks,
                     int compression, int predictor, int rows, int width,
                     int spp, int elem_size, uint8_t* out, int n_threads) {
  if (n_blocks < 0 || rows <= 0 || width <= 0 || spp <= 0) return kErrBadArg;
  if (elem_size != 1 && elem_size != 2 && elem_size != 4 && elem_size != 8)
    return kErrBadArg;
  if (compression != kCompNone && compression != kCompDeflateAdobe &&
      compression != kCompDeflateOld && compression != kCompLzw)
    return kErrBadArg;
  if (predictor == 2 && elem_size == 8) return kErrBadArg;  // floats only
  const size_t row_bytes = static_cast<size_t>(width) * spp * elem_size;
  const size_t slot_bytes = static_cast<size_t>(rows) * row_bytes;

  return run_blocks(n_blocks, n_threads, [&](int i) -> int {
    // Overflow-safe: offsets[i] + counts[i] can wrap in uint64 for corrupt
    // or malicious IFD entries, bypassing a naive sum check.
    if (offsets[i] > file_len || counts[i] > file_len - offsets[i])
      return kErrShortData;
    if (block_rows[i] > static_cast<uint64_t>(rows)) return kErrBadArg;
    const size_t want = block_rows[i] * row_bytes;
    const uint8_t* src = file_data + offsets[i];
    uint8_t* dst = out + static_cast<size_t>(i) * slot_bytes;
    if (compression == kCompNone) {
      if (counts[i] < want) return kErrShortData;
      std::memcpy(dst, src, want);
    } else if (compression == kCompLzw) {
      int rc = lzw_decode(src, counts[i], dst, want);
      if (rc != kOk) return rc;
    } else {
      int rc = inflate_block(src, counts[i], dst, want);
      if (rc != kOk) return rc;
    }
    if (predictor == 2)
      apply_predictor(dst, static_cast<int>(block_rows[i]), width, spp,
                      elem_size, /*undo=*/true);
    return kOk;
  });
}

// Encode n_blocks equal-geometry blocks with optional predictor + deflate
// or LZW.
//
//   blocks       n_blocks contiguous input blocks (modified in place when
//                predictor=2 — pass a scratch copy)
//   compression  8 (deflate) or 5 (LZW)
//   out          caller-allocated, n_blocks * bound bytes
//   bound        per-block output capacity (deflate:
//                >= lt_deflate_bound(block_bytes); LZW: >= 2*block_bytes+64
//                — 12-bit codes for 8-bit symbols is the worst case)
//   out_sizes    per-block compressed byte counts (written)
//   level        zlib level (6 matches the Python writer; ignored for LZW)
int lt_encode_blocks(uint8_t* blocks, int n_blocks, int compression,
                     int predictor, int rows, int width, int spp,
                     int elem_size, uint8_t* out, uint64_t bound,
                     uint64_t* out_sizes, int level, int n_threads) {
  if (n_blocks < 0 || rows <= 0 || width <= 0 || spp <= 0) return kErrBadArg;
  if (elem_size != 1 && elem_size != 2 && elem_size != 4 && elem_size != 8)
    return kErrBadArg;
  if (predictor == 2 && elem_size == 8) return kErrBadArg;
  if (compression != kCompDeflateAdobe && compression != kCompLzw)
    return kErrBadArg;
  const size_t block_bytes =
      static_cast<size_t>(rows) * width * spp * elem_size;
  if (compression == kCompDeflateAdobe) {
    if (bound < compressBound(static_cast<uLong>(block_bytes)))
      return kErrBadArg;
  } else {
    if (bound < 2 * block_bytes + 64) return kErrBadArg;
  }

  return run_blocks(n_blocks, n_threads, [&](int i) -> int {
    uint8_t* src = blocks + static_cast<size_t>(i) * block_bytes;
    if (predictor == 2)
      apply_predictor(src, rows, width, spp, elem_size, /*undo=*/false);
    if (compression == kCompLzw)
      return lzw_encode(src, block_bytes, out + static_cast<size_t>(i) * bound,
                        bound, &out_sizes[i]);
    uLongf dst_len = static_cast<uLongf>(bound);
    int rc = compress2(out + static_cast<size_t>(i) * bound, &dst_len, src,
                       static_cast<uLong>(block_bytes), level);
    if (rc != Z_OK) return kErrDeflate;
    out_sizes[i] = dst_len;
    return kOk;
  });
}

uint64_t lt_deflate_bound(uint64_t n) {
  return compressBound(static_cast<uLong>(n));
}

// Write a STORE-mode (method 0) ZIP from pre-assembled members — the
// manifest's per-tile .npz artifact without Python's zipfile in the hot
// path.  Each member i is the concatenation of a prefix (the .npy header
// the Python side renders) and a payload (the raw array bytes); CRC32 runs
// threaded across members (zlib crc32 releases nothing — there is no GIL
// here — and it is the only non-I/O cost of a stored zip), then one
// sequential buffered pass writes local headers + data + central
// directory.  Classic (non-zip64) layout only: any member or the whole
// file reaching u32 limits returns kErrBadArg and the caller falls back to
// Python's zipfile (which force-flags zip64).  np.load reads the result
// like any np.savez output.
//
//   path                        output file (created/truncated; caller
//                               handles atomic-rename)
//   n                           member count
//   name_ptrs/name_lens         member names (ASCII, include ".npy")
//   head_ptrs/head_lens         per-member prefix bytes
//   data_ptrs/data_lens         per-member payload bytes
//   n_threads                   CRC threading (0 = hardware)
int lt_write_store_zip(const char* path, int n,
                       const uint8_t* const* name_ptrs,
                       const uint64_t* name_lens,
                       const uint8_t* const* head_ptrs,
                       const uint64_t* head_lens,
                       const uint8_t* const* data_ptrs,
                       const uint64_t* data_lens, int n_threads) {
  constexpr uint64_t kU32Max = 0xFFFFFFFFull;
  constexpr uint64_t kU16Max = 0xFFFFull;
  // classic zip only: the EOCD member counts are u16, so n past that must
  // fall back to Python's zipfile (zip64), not truncate silently
  if (!path || n <= 0 || static_cast<uint64_t>(n) > kU16Max)
    return kErrBadArg;

  std::vector<uint64_t> sizes(n), offsets(n);
  std::vector<uint32_t> crcs(n);
  uint64_t pos = 0;
  for (int i = 0; i < n; ++i) {
    if (!name_ptrs[i] || name_lens[i] == 0 || name_lens[i] > kU16Max)
      return kErrBadArg;
    sizes[i] = head_lens[i] + data_lens[i];
    if (sizes[i] > kU32Max) return kErrBadArg;
    offsets[i] = pos;
    pos += 30 + name_lens[i] + sizes[i];  // local header + name + data
    if (pos > kU32Max) return kErrBadArg;
  }

  int rc = run_blocks(n, n_threads, [&](int i) -> int {
    uLong c = crc32(0L, Z_NULL, 0);
    // crc32's uInt length caps each call at 4 GB-1; sizes[i] <= u32 max,
    // but chunk anyway so the bound never binds
    const uint8_t* parts[2] = {head_ptrs[i], data_ptrs[i]};
    const uint64_t lens[2] = {head_lens[i], data_lens[i]};
    for (int p = 0; p < 2; ++p) {
      uint64_t done = 0;
      while (done < lens[p]) {
        uInt step = static_cast<uInt>(
            std::min<uint64_t>(lens[p] - done, 1u << 30));
        c = crc32(c, parts[p] + done, step);
        done += step;
      }
    }
    crcs[i] = static_cast<uint32_t>(c);
    return kOk;
  });
  if (rc != kOk) return rc;

  FILE* f = std::fopen(path, "wb");
  if (!f) return kErrBadArg;
  std::vector<uint8_t> big_buf(1 << 20);
  std::setvbuf(f, reinterpret_cast<char*>(big_buf.data()), _IOFBF,
               big_buf.size());

  auto put16 = [&](uint32_t v) {
    uint8_t b[2] = {static_cast<uint8_t>(v), static_cast<uint8_t>(v >> 8)};
    std::fwrite(b, 1, 2, f);
  };
  auto put32 = [&](uint32_t v) {
    uint8_t b[4] = {static_cast<uint8_t>(v), static_cast<uint8_t>(v >> 8),
                    static_cast<uint8_t>(v >> 16),
                    static_cast<uint8_t>(v >> 24)};
    std::fwrite(b, 1, 4, f);
  };

  for (int i = 0; i < n; ++i) {
    put32(0x04034b50);          // local file header
    put16(20); put16(0); put16(0);  // version, flags, method=store
    put16(0); put16(0);         // mod time/date (fixed: reproducible files)
    put32(crcs[i]);
    put32(static_cast<uint32_t>(sizes[i]));  // compressed == uncompressed
    put32(static_cast<uint32_t>(sizes[i]));
    put16(static_cast<uint32_t>(name_lens[i]));
    put16(0);                   // extra len
    std::fwrite(name_ptrs[i], 1, name_lens[i], f);
    if (head_lens[i]) std::fwrite(head_ptrs[i], 1, head_lens[i], f);
    if (data_lens[i]) std::fwrite(data_ptrs[i], 1, data_lens[i], f);
  }
  const uint64_t cd_off = pos;
  uint64_t cd_size = 0;
  for (int i = 0; i < n; ++i) {
    put32(0x02014b50);          // central directory header
    put16(20); put16(20); put16(0); put16(0);  // made-by, need, flags, method
    put16(0); put16(0);         // time/date
    put32(crcs[i]);
    put32(static_cast<uint32_t>(sizes[i]));
    put32(static_cast<uint32_t>(sizes[i]));
    put16(static_cast<uint32_t>(name_lens[i]));
    put16(0); put16(0);         // extra, comment
    put16(0); put16(0);         // disk, internal attrs
    put32(0);                   // external attrs
    put32(static_cast<uint32_t>(offsets[i]));
    std::fwrite(name_ptrs[i], 1, name_lens[i], f);
    cd_size += 46 + name_lens[i];
  }
  if (cd_off + cd_size + 22 > kU32Max) {  // end record offsets must fit too
    std::fclose(f);
    std::remove(path);
    return kErrBadArg;
  }
  put32(0x06054b50);            // end of central directory
  put16(0); put16(0);
  put16(static_cast<uint32_t>(n));
  put16(static_cast<uint32_t>(n));
  put32(static_cast<uint32_t>(cd_size));
  put32(static_cast<uint32_t>(cd_off));
  put16(0);                     // comment len
  if (std::fflush(f) != 0 || std::ferror(f)) {
    std::fclose(f);
    std::remove(path);
    return kErrDeflate;  // an I/O failure, surfaced as a generic write error
  }
  return std::fclose(f) == 0 ? kOk : kErrDeflate;
}

}  // extern "C"
