"""Headline benchmark: segmentation throughput in pixels/sec on one chip.

Measures the north-star metric from BASELINE.json / SURVEY.md §6 — LandTrendr
temporal segmentation of a 38+-year NBR stack, target ≥ 10M pixels/sec/chip —
on whatever single device JAX provides (the real TPU chip under the driver;
CPU when forced).  Prints exactly ONE JSON line:

    {"metric": ..., "value": N, "unit": "pixels/sec/chip", "vs_baseline": N}

``vs_baseline`` is value / 10e6 (the north-star target; the reference
publishes no numbers of its own — BASELINE.json "published": {}).

Methodology: realistic synthetic disturbance series (patchy events, regrowth,
noise, ~8% masked observations) in float32, device-resident (the metric is
kernel throughput; host→HBM feeding is the driver pipeline's job and is
reported separately in its run summaries).  One untimed warm-up step
compiles the kernel; then ``REPS`` timed runs with ``block_until_ready``;
the reported value uses the best rep.  After timing, a small slice of the
outputs is fetched to the host and checked finite — a faulted asynchronous
execution (which can "complete" instantly) therefore invalidates the run
instead of inflating it.  If the batch does not fit in HBM the benchmark
halves it and retries (the kernel's working set scales linearly with the
pixel axis).

Env overrides: LT_BENCH_PX (default 1048576), LT_BENCH_YEARS (40),
LT_BENCH_REPS (5).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def make_series(px: int, ny: int, seed: int = 7) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Disturbance-positive NBR-like series + mask, float32."""
    rng = np.random.default_rng(seed)
    years = np.arange(1984, 1984 + ny, dtype=np.int32)
    base = rng.uniform(0.55, 0.75, size=(px, 1)).astype(np.float32)
    t = np.arange(ny, dtype=np.float32)[None, :]
    disturbed = rng.uniform(size=(px, 1)) < 0.35
    d_year = rng.integers(5, ny - 5, size=(px, 1))
    mag = rng.uniform(0.15, 0.5, size=(px, 1)).astype(np.float32)
    rec = rng.uniform(0.03, 0.15, size=(px, 1)).astype(np.float32)
    dt = np.maximum(t - d_year, 0.0).astype(np.float32)
    traj = base - np.where(disturbed & (t >= d_year), mag * np.exp(-rec * dt), 0.0)
    traj += rng.normal(0.0, 0.012, size=(px, ny)).astype(np.float32)
    mask = rng.uniform(size=(px, ny)) > 0.08
    return years, (-traj).astype(np.float32), mask


def _is_oom(e: Exception) -> bool:
    s = str(e)
    return "memory" in s.lower() or "RESOURCE_EXHAUSTED" in s


def _run_once(px: int, ny: int, reps: int) -> float:
    """Time the kernel at one batch size; returns best-rep seconds.

    Raises on device/validity failure so the caller can back off.
    """
    import jax

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels

    dev = jax.devices()[0]
    params = LTParams()
    years_np, vals_np, mask_np = make_series(px, ny)
    years = jax.device_put(years_np, dev)
    vals = jax.device_put(vals_np, dev)
    mask = jax.device_put(mask_np, dev)

    # warm-up: compile + first run, with a host fetch proving it executed
    out = jax_segment_pixels(years, vals, mask, params)
    jax.block_until_ready(out)
    probe = np.asarray(out.rmse[: min(px, 64)])
    if not np.isfinite(probe).all():
        raise RuntimeError("warm-up produced non-finite rmse")

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax_segment_pixels(years, vals, mask, params)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)

    # validity fetch: a faulted async execution must fail here, not report
    probe = np.asarray(out.rmse[: min(px, 64)])
    if not np.isfinite(probe).all():
        raise RuntimeError("timed run produced non-finite rmse")
    return best


def main() -> int:
    px = int(os.environ.get("LT_BENCH_PX", 1048576))
    ny = int(os.environ.get("LT_BENCH_YEARS", 40))
    reps = int(os.environ.get("LT_BENCH_REPS", 5))

    best = None
    last_err: Exception | None = None
    for _ in range(4):  # back off on OOM: kernel memory is linear in px
        try:
            best = _run_once(px, ny, reps)
            break
        except Exception as e:
            last_err = e
            if _is_oom(e) and px > 4096:
                px //= 2
                continue
            raise
    if best is None:
        raise RuntimeError(f"benchmark failed at px={px}") from last_err

    value = px / best
    print(
        json.dumps(
            {
                "metric": f"landtrendr_segmentation_throughput_{ny}yr_nbr",
                "value": round(value, 1),
                "unit": "pixels/sec/chip",
                "vs_baseline": round(value / 10e6, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
