"""Headline benchmark: segmentation throughput in pixels/sec on one chip.

Measures the north-star metric from BASELINE.json / SURVEY.md §6 — LandTrendr
temporal segmentation of a 38+-year NBR stack, target ≥ 10M pixels/sec/chip —
on whatever single device JAX provides (the real TPU chip under the driver;
CPU when forced).  Prints exactly ONE JSON line:

    {"metric": ..., "value": N, "unit": "pixels/sec/chip", "vs_baseline": N}

``vs_baseline`` is value / 10e6 (the north-star target; the reference
publishes no numbers of its own — BASELINE.json "published": {}).

Methodology: realistic synthetic disturbance series (patchy events, regrowth,
noise, ~8% masked observations) in float32, device-resident (the metric is
kernel throughput; host→HBM feeding is the driver pipeline's job and is
reported separately in its run summaries).  Two timing modes:

* ``chain`` (default on accelerators): one jitted ``lax.fori_loop`` applies
  the kernel ``K`` times with a data dependency between steps (step ``i+1``
  segments step ``i``'s despiked series), and the timed quantity is
  dispatch → scalar fetch of a probe reduced across all steps.  This is
  the only methodology that stays valid on remote/tunneled devices (the
  axon TPU), where ``block_until_ready`` was OBSERVED to return before
  execution (0.2 ms "runs" of a multi-ms kernel) and identical-input
  replays can be serviced suspiciously fast — the data dependency defeats
  both, and the single round trip amortizes tunnel latency that would
  otherwise dominate per-rep timing.  Each rep times the K-chain AND a
  short ``K/8``-chain of the same compiled program (the loop bound is a
  traced value, so both share one cache entry); the reported ``value`` is
  the paired-K net rate ``px*(K-K/8) / (t_K - t_K/8)`` — the constant
  dispatch+fetch round trip cancels in the subtraction, leaving the
  on-device kernel rate a local host would see (the north-star quantity).
  ``value_lower_bound`` (= ``px*K / t_K``, everything included) is always
  reported alongside; if the subtraction is noise-dominated (delta < 10%
  of the long window) the lower bound IS the value.
* ``loop`` (default on cpu): the classic warm-up + ``REPS`` timed runs
  with ``block_until_ready``, best rep reported.

After timing, outputs are fetched and checked finite — a faulted
asynchronous execution (which can "complete" instantly) therefore
invalidates the run instead of inflating it.  If the batch does not fit
in HBM — or the device faults, observed on the tunneled chip at large
batches — the benchmark halves ``px`` and retries (the kernel's working
set scales linearly with the pixel axis).

Robustness (round-1 failure mode: TPU backend init both *erroring* with
``UNAVAILABLE: TPU backend setup/compile error`` and *hanging* >9 min at 0%
CPU): the measurement runs in a CHILD process so a hung backend init is
killable; the parent retries with backoff on init errors/hangs and, if every
attempt fails, still prints one parseable JSON diagnostic line (value 0 +
"error") instead of a bare traceback.

Env overrides: LT_BENCH_PX (default 1048576), LT_BENCH_YEARS (40),
LT_BENCH_REPS (5; chain mode consumes reps as max(1, reps//2) long/short
window PAIRS — 4 timed windows per pair, so reps=5 runs 2 pairs),
LT_BENCH_ATTEMPTS (4), LT_BENCH_TIMEOUT (seconds per
attempt, default 900 — TPU first-compile alone can take tens of seconds),
LT_BENCH_MODE ("chain"/"loop"; default picks by device platform),
LT_BENCH_CHAIN_K (chain steps, default 16),
LT_BENCH_PLATFORM (force a JAX platform, e.g. "cpu" for smoke tests — set
via ``jax.config``, because this container's interpreter boot hook selects
``jax_platforms="axon,cpu"`` programmatically, which outranks the
JAX_PLATFORMS env var).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

_EXIT_INIT_HANG = 3
_T0 = time.perf_counter()  # process birth: time-to-first-timed-rep anchor


def _mark_warmup_done() -> None:
    """Stderr marker for time-to-first-timed-rep — the quantity the
    persistent compile cache exists to shrink (tools/cache_proof.py parses
    this line; the round-3 TPU window died before ever reaching it)."""
    print(
        f"bench: warm-up done at {time.perf_counter() - _T0:.1f}s"
        " since process start",
        file=sys.stderr,
        flush=True,
    )


def make_series(px: int, ny: int, seed: int = 7) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Disturbance-positive NBR-like series + mask, float32."""
    rng = np.random.default_rng(seed)
    years = np.arange(1984, 1984 + ny, dtype=np.int32)
    base = rng.uniform(0.55, 0.75, size=(px, 1)).astype(np.float32)
    t = np.arange(ny, dtype=np.float32)[None, :]
    disturbed = rng.uniform(size=(px, 1)) < 0.35
    d_year = rng.integers(5, ny - 5, size=(px, 1))
    mag = rng.uniform(0.15, 0.5, size=(px, 1)).astype(np.float32)
    rec = rng.uniform(0.03, 0.15, size=(px, 1)).astype(np.float32)
    dt = np.maximum(t - d_year, 0.0).astype(np.float32)
    traj = base - np.where(disturbed & (t >= d_year), mag * np.exp(-rec * dt), 0.0)
    traj += rng.normal(0.0, 0.012, size=(px, ny)).astype(np.float32)
    mask = rng.uniform(size=(px, ny)) > 0.08
    return years, (-traj).astype(np.float32), mask


def _is_oom(e: Exception) -> bool:
    s = str(e)
    return "memory" in s.lower() or "RESOURCE_EXHAUSTED" in s


def _is_worker_crash(e: Exception) -> bool:
    """"UNAVAILABLE: TPU worker process crashed or restarted" — observed
    round 4 to hit EVERY batch size for minutes after a prior client's
    fault or disconnect, then clear on its own.  A wedged-worker state,
    not a batch-size problem: the right response is to wait for the
    worker to come back and retry at the SAME px, not to halve."""
    return "worker process crashed" in str(e).lower()


def _is_device_fault(e: Exception) -> bool:
    """Device-side execution faults observed on the tunneled axon chip at
    large batches ("UNAVAILABLE: TPU device error — often a kernel fault")
    while smaller batches of the SAME program run clean — treated like OOM
    for back-off purposes, since they correlate with batch size."""
    s = str(e).lower()
    # deliberately NARROW: bare gRPC "UNAVAILABLE" also covers transient
    # tunnel drops, which should be retried at the same px by the parent,
    # not misread as a batch-size problem; "worker process crashed" is the
    # wedged-worker state (see _is_worker_crash), also not size-related
    return not _is_worker_crash(e) and ("device error" in s or "kernel fault" in s)


def _first_device(init_timeout: float):
    """``jax.devices()[0]`` under a watchdog: a hung backend init kills the
    process with a distinctive exit code instead of stalling forever (the
    observed round-1 failure mode — init parked at 0% CPU for >9 min)."""
    import jax

    forced = os.environ.get("LT_BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    done = threading.Event()

    def watchdog():
        if not done.wait(init_timeout):
            print(
                f"bench: backend init exceeded {init_timeout:.0f}s watchdog",
                file=sys.stderr,
                flush=True,
            )
            os._exit(_EXIT_INIT_HANG)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        t0 = time.perf_counter()
        dev = jax.devices()[0]
        print(
            f"bench: backend up in {time.perf_counter() - t0:.1f}s "
            f"(platform={dev.platform})",
            file=sys.stderr,
            flush=True,
        )
        return dev
    finally:
        done.set()


#: resolved by _make_runner on each build: which kernel the bench actually
#: ran ("pallas"/"xla") — recorded in the result line so a consumer can
#: tell the implementations apart without trusting env vars
_RESOLVED_IMPL = "xla"


def _make_runner(px: int, ny: int):
    """(device arrays, single-application fn) for the size-appropriate kernel."""
    global _RESOLVED_IMPL
    import jax

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import (
        jax_segment_pixels,
        jax_segment_pixels_chunked,
    )
    from land_trendr_tpu.parallel.mesh import pad_to_multiple

    params = LTParams()
    years_np, vals_np, mask_np = make_series(px, ny)
    chunk = int(os.environ.get("LT_BENCH_CHUNK", 262144))
    impl = os.environ.get("LT_BENCH_IMPL", "pallas")
    use_pallas = impl == "pallas" and jax.default_backend() == "tpu"
    if use_pallas:
        from land_trendr_tpu.ops.segment_pallas import (
            jax_segment_pixels_pallas,
            jax_segment_pixels_pallas_chunked,
        )
    from land_trendr_tpu.ops.tile import PALLAS_BLOCK
    if px > chunk:
        # indivisible px pads up with fully-masked rows (never a silent
        # fallback to the unchunked kernel — that is the OOM path);
        # throughput still counts only the real pixels
        vals_np, mask_np, _ = pad_to_multiple(vals_np, mask_np, chunk)

        if use_pallas and (chunk <= PALLAS_BLOCK or chunk % PALLAS_BLOCK == 0):
            _RESOLVED_IMPL = "pallas"
            def run(y, v, m):
                return jax_segment_pixels_pallas_chunked(y, v, m, params, chunk)
        else:
            _RESOLVED_IMPL = "xla"
            def run(y, v, m):
                return jax_segment_pixels_chunked(y, v, m, params, chunk)
    else:
        # the Pallas block is min(PALLAS_BLOCK, px): any smaller px
        # divides by itself; larger px must divide by the block
        if use_pallas and (px < PALLAS_BLOCK or px % PALLAS_BLOCK == 0):
            _RESOLVED_IMPL = "pallas"
            def run(y, v, m):
                return jax_segment_pixels_pallas(y, v, m, params)
        else:
            _RESOLVED_IMPL = "xla"
            def run(y, v, m):
                return jax_segment_pixels(y, v, m, params)

    return years_np, vals_np, mask_np, run


def _run_chained(
    dev, px: int, ny: int, reps: int, k: int
) -> tuple[float, float, int]:
    """Time K data-dependent kernel applications in ONE dispatch.

    Returns ``(best_k_seconds, median_delta_seconds, k_short)``
    (all present — n_pairs >= 1 guarantees a delta): the
    best wall seconds for the full K-chain window (dispatch + K kernels
    + one scalar fetch) and the median over window PAIRS of the
    pair-averaged difference between adjacent K- and ``k_short``-chain
    windows of the SAME compiled program — each pair runs the two
    orders (long-short, then short-long) and averages its two deltas,
    so monotone congestion drift cancels within the pair.  The delta
    lets the caller cancel the constant per-dispatch cost (tunnel RPC +
    fetch — ~seconds on the axon link, TPU_PROBE_r03.md):

        net px/s = px * (k - k_short) / median(pair-averaged deltas)

    which is the on-device kernel rate a LOCAL host would observe — the
    quantity the north-star metric describes — while ``px*k / t_k``
    stays the conservative everything-included lower bound.

    The chain length is a TRACED ``lax.fori_loop`` bound, so one
    compiled program serves every K: the short window re-uses the warm
    cache entry instead of paying a second TPU compile inside a
    precarious availability window.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    years_np, vals_np, mask_np, run = _make_runner(px, ny)

    @jax.jit
    def chained(y, v, m, steps):
        def body(_i, carry):
            v_cur, acc = carry
            out = run(y, v_cur, m)
            # feeding the despiked series (same shape/orientation as the
            # input) into the next step makes every step data-depend on
            # the previous one — no cache or scheduler can elide a step.
            # The probe reduces per-step outputs whose producers span the
            # whole pipeline (rmse: selected-model SSE; n_vertices:
            # selection + vertex bookkeeping), so no stage is dead code;
            # rmse.sum() is NaN-propagating over EVERY pixel, so a fault
            # anywhere in the batch fails the finite check below.
            probe = out.rmse.sum() + out.n_vertices.sum().astype(out.rmse.dtype)
            return out.despiked, acc + probe
        final, acc = lax.fori_loop(
            0, steps, body, (v, jnp.float32(0.0))
        )
        return acc + final[0, 0]

    years = jax.device_put(years_np, dev)
    mask = jax.device_put(mask_np, dev)
    vals0 = jax.device_put(vals_np, dev)

    # every rep gets a DISTINCT input (tiny masked-safe offset): byte-
    # identical (program, inputs) replays are exactly what a caching tunnel
    # runtime could service without running anything, and best-of-reps
    # would then select the bogus rep.  The offset is applied ON DEVICE
    # (ADVICE r3: pre-placing reps+1 full batches held ~640 MB HBM at the
    # default 1M px × 40 y, shrinking the largest runnable batch), so at
    # most two copies are ever resident: the base and one derived input.
    @jax.jit
    def perturb(v, i):
        return v + jnp.float32(1e-6) * i

    # warm-up: compile both programs + first chain; float() is the sync
    # (see docstring).  The timed window includes one perturb (elementwise,
    # O(px·ny) — noise against K full kernel applications, and the chain
    # value is documented as a lower bound anyway).
    r = float(chained(years, perturb(vals0, 0), mask, k))
    if not np.isfinite(r):
        raise RuntimeError("warm-up chain produced non-finite probe")
    _mark_warmup_done()

    def timed(steps: int, i: int) -> float:
        t0 = time.perf_counter()
        r = float(chained(years, perturb(vals0, i), mask, steps))
        dt = time.perf_counter() - t0
        if not np.isfinite(r):
            raise RuntimeError("timed chain produced non-finite probe")
        return dt

    k_short = max(1, k // 8)
    best = float("inf")
    pair_deltas: list[float] = []
    # interleave long/short windows so drifting tunnel congestion
    # (observed round 3: honest readings then a 200× slowdown minutes
    # later) degrades both sides of the subtraction together instead of
    # biasing one.  The subtraction is taken between ADJACENT windows
    # (same congestion regime): min-of-longs minus min-of-shorts would
    # let one lucky long window + one unlucky short window inflate the
    # net rate unboundedly.  Reps are grouped into PAIRS with opposite
    # within-pair order (long-short then short-long): under monotone
    # drift the two orders bias their deltas in opposite directions by
    # the same magnitude, so the pair average cancels the drift term
    # exactly — a median over an odd count of one-sided deltas would
    # instead pick a biased element.
    n_pairs = max(1, reps // 2)
    seq = 0
    for _ in range(n_pairs):
        seq += 1
        t_long_a = timed(k, seq)
        seq += 1
        t_short_a = timed(k_short, seq)
        seq += 1
        t_short_b = timed(k_short, seq)
        seq += 1
        t_long_b = timed(k, seq)
        best = min(best, t_long_a, t_long_b)
        pair_deltas.append(
            ((t_long_a - t_short_a) + (t_long_b - t_short_b)) / 2.0
        )
    # n_pairs >= 1, so there is always at least one delta
    median_delta = float(np.median(pair_deltas))
    return best, median_delta, k_short


def _run_once(dev, px: int, ny: int, reps: int) -> float:
    """Time the kernel at one batch size; returns best-rep seconds.

    Raises on device/validity failure so the caller can back off.

    Batches larger than ``LT_BENCH_CHUNK`` (default 256K px) run through
    the chunked kernel: transient HBM stays bounded at one chunk while
    outputs for the whole batch accumulate — the production path the tile
    driver uses for ≥1024² tiles, and the configuration a real chip should
    be benched in (the unchunked 1M-px batch was the round-1/2 OOM-backoff
    trigger).
    """
    import jax

    years_np, vals_np, mask_np, run = _make_runner(px, ny)

    years = jax.device_put(years_np, dev)
    vals = jax.device_put(vals_np, dev)
    mask = jax.device_put(mask_np, dev)

    # warm-up: compile + first run, with a host fetch proving it executed
    out = run(years, vals, mask)
    jax.block_until_ready(out)
    probe = np.asarray(out.rmse[: min(px, 64)])
    if not np.isfinite(probe).all():
        raise RuntimeError("warm-up produced non-finite rmse")
    _mark_warmup_done()

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(years, vals, mask)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)

    # validity fetch: a faulted async execution must fail here, not report
    probe = np.asarray(out.rmse[: min(px, 64)])
    if not np.isfinite(probe).all():
        raise RuntimeError("timed run produced non-finite rmse")
    return best


def _child_main() -> int:
    """One measurement attempt; prints the JSON result line on success."""
    px = int(os.environ.get("LT_BENCH_PX", 1048576))
    ny = int(os.environ.get("LT_BENCH_YEARS", 40))
    reps = int(os.environ.get("LT_BENCH_REPS", 5))
    init_timeout = float(os.environ.get("LT_BENCH_TIMEOUT", 900)) * 0.5

    # persistent compile cache: an attempt that compiles and then dies at
    # readback (the round-3 window post-mortem) still leaves the compiled
    # program on disk for the next attempt — see utils/compilation_cache.py
    from land_trendr_tpu.utils.compilation_cache import enable_persistent_cache

    enable_persistent_cache()

    dev = _first_device(init_timeout)
    mode = os.environ.get("LT_BENCH_MODE") or (
        "loop" if dev.platform == "cpu" else "chain"
    )
    if mode not in ("chain", "loop"):
        raise ValueError(f"LT_BENCH_MODE={mode!r} not 'chain'|'loop'")
    k = int(os.environ.get("LT_BENCH_CHAIN_K", 16))

    best = None
    median_delta: float | None = None
    k_short = 0
    last_err: Exception | None = None
    crash_waits = 0
    # the parent kills this child at LT_BENCH_TIMEOUT: never start a
    # crash-recovery sleep the budget can't absorb (plus headroom for the
    # retried measurement itself), or the wait gets killed mid-recovery
    # and the next attempt re-pays backend init + compile from scratch
    budget = float(os.environ.get("LT_BENCH_TIMEOUT", 900))
    # separate budgets: crash waits (same px) must not consume the halving
    # budget, or two early worker crashes leave the 1M→4096 backoff chain
    # one iteration short of ever trying the floor size
    halvings = 0
    while halvings <= 9:  # back off: kernel memory is linear in px, and
        # the tunneled chip's device faults correlate with batch size too
        try:
            if mode == "chain":
                best, median_delta, k_short = _run_chained(dev, px, ny, reps, k)
            else:
                best = _run_once(dev, px, ny, reps)
            break
        except Exception as e:
            last_err = e
            elapsed = time.perf_counter() - _T0
            if (
                _is_worker_crash(e)
                and crash_waits < 4
                and elapsed + 60 < 0.75 * budget
            ):
                crash_waits += 1
                print(
                    f"bench: worker crashed (wait {crash_waits}/4, 60s, "
                    f"same px={px}, {elapsed:.0f}s/{budget:.0f}s used)",
                    file=sys.stderr,
                    flush=True,
                )
                time.sleep(60)
                continue
            if (_is_oom(e) or _is_device_fault(e)) and px > 4096:
                halvings += 1
                print(
                    f"bench: px={px} failed ({str(e)[:120]}); halving",
                    file=sys.stderr,
                    flush=True,
                )
                px //= 2
                continue
            raise
    if best is None:
        raise RuntimeError(f"benchmark failed at px={px}") from last_err

    n_runs = k if mode == "chain" else 1
    lower_bound = px * n_runs / best
    value = lower_bound
    chunk = int(os.environ.get("LT_BENCH_CHUNK", 262144))
    extra = {
        "px": px,
        "platform": os.environ.get("LT_BENCH_PLATFORM") or "default",
        # the ACTUAL platform measured (the axon plugin can fail init and
        # fall back to cpu — a consumer must be able to tell a TPU number
        # from a fallback-CPU number without trusting env vars)
        "device_platform": dev.platform,
        "chunked": px > chunk,
        "mode": mode,
        "impl": _RESOLVED_IMPL,
    }
    if mode == "chain":
        extra["chain_k"] = k
        extra["value_lower_bound"] = round(lower_bound, 1)
        extra["t_chain_s"] = round(best, 4)
        # paired-K subtraction: the K- and k_short-windows run the SAME
        # compiled program, so their difference contains exactly
        # (k - k_short) kernel applications and ZERO dispatch/fetch round
        # trips — the constant tunnel cost cancels.  Accepted only when
        # the delta is a meaningful fraction of the long window
        # (>= 10% of t_chain and positive); otherwise the long window is
        # dispatch-dominated at this px and the subtraction would divide
        # by timing noise, so the conservative lower bound stands alone.
        # chain mode always produces a median delta (n_pairs >= 1)
        extra["median_delta_s"] = round(median_delta, 4)
        extra["k_short"] = k_short
        if median_delta >= 0.10 * best and k > k_short:
            net = px * (k - k_short) / median_delta
            if net < lower_bound:
                # px*K/t_best is PROVEN (that window strictly contained
                # the K executions), so when the median-based central
                # estimate lands below it the bound is simply the better
                # (and safe) number.  Normal on low-dispatch-overhead
                # devices: min-of-longs beats a median-derived rate
                # whenever rep spread exceeds the dispatch cost being
                # cancelled — not an anomaly, and the note must describe
                # the number actually reported.
                extra["clamped_to_lower_bound"] = True
                value = lower_bound
                extra["note"] = (
                    "paired-K net estimate below the proven best-window "
                    "bound px*K/t_chain (dispatch overhead small vs rep "
                    "spread — expected off-tunnel); value IS that proven "
                    "bound, dispatch+fetch round trip included."
                )
            else:
                value = net
                extra["note"] = (
                    "value is paired-K net device throughput: "
                    "px*(K-k_short)/median(pair-averaged "
                    "t_K-t_short deltas, opposite within-pair "
                    "order) on one compiled program; the constant "
                    "dispatch+fetch round trip cancels per window "
                    "pair. value_lower_bound is the everything-"
                    "included window rate."
                )
        else:
            extra["note"] = (
                "chain window is dispatch-dominated at this px "
                f"(median paired delta {median_delta:.3f}s < 10% of "
                "t_chain); value is the conservative lower bound "
                "(window includes one dispatch+fetch round trip)"
            )
    print(_result_line(ny, value, extra=extra), flush=True)
    return 0


def _result_line(
    ny: int, value: float, error: str | None = None, extra: dict | None = None
) -> str:
    """The ONE output line — shared by success and diagnostic paths so the
    metric name / schema can never desynchronize between them."""
    rec = {
        "metric": f"landtrendr_segmentation_throughput_{ny}yr_nbr",
        "value": round(value, 1),
        "unit": "pixels/sec/chip",
        "vs_baseline": round(value / 10e6, 4),
    }
    if extra:
        rec.update(extra)
    if error is not None:
        rec["error"] = error[-2000:]
    return json.dumps(rec)


def _mesh_main(n_dev: int) -> int:
    """``--mesh N``: sharded-kernel scaling over an N-device virtual mesh.

    VERDICT r4 #7: multi-chip hardware does not exist in this environment,
    so the scaling MECHANICS (mesh build, pixel-axis sharding, per-device
    bookkeeping, N-vs-1 efficiency) are exercised on the virtual CPU mesh
    — the same code path a real pod would run — and the artifact records
    per-device rates so the day multi-chip hardware exists the same
    command produces real numbers.  Emits ONE JSON line (schema mirrors
    the headline metric, metric name suffixed ``_meshN``); this mode is
    opt-in via argv and never runs under the driver's plain invocation.
    """
    import numpy as np  # noqa: F811 (child re-import before jax init)

    import jax

    # the container's sitecustomize preloads jax with the axon platform,
    # OUTRANKING the JAX_PLATFORMS env var (see tests/conftest.py); backends
    # initialise lazily, so flipping the config before any device touch
    # still selects the virtual CPU mesh
    if jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")

    import land_trendr_tpu.ops  # noqa: F401 (break the tile<->mesh import cycle)
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.parallel.mesh import (
        make_mesh,
        segment_pixels_sharded,
        shard_pixels,
        summarize_sharded,
    )

    from land_trendr_tpu.parallel.mesh import pad_to_multiple

    px = int(os.environ.get("LT_BENCH_MESH_PX", 65536))
    ny = int(os.environ.get("LT_BENCH_YEARS", 40))
    reps = int(os.environ.get("LT_BENCH_REPS", 3))
    devs = jax.devices()
    if len(devs) < n_dev:
        print(_result_line(ny, 0.0, error=(
            f"--mesh {n_dev} needs {n_dev} devices; only {len(devs)} "
            "visible (run via the parent so XLA_FLAGS is set before "
            "jax initialises)")), flush=True)
        return 1
    params = LTParams()
    years_np, vals_np, mask_np = make_series(px, ny)
    # any device count divides after padding with fully-masked rows (the
    # throughput denominator stays the REAL px; padding is no-fit work)
    vals_np, mask_np, _ = pad_to_multiple(vals_np, mask_np, n_dev)

    def run_on(mesh_devs) -> float:
        mesh = make_mesh(mesh_devs)
        vals, mask = shard_pixels(mesh, vals_np, mask_np)
        best = float("inf")
        for rep in range(reps + 1):  # rep 0 is the compile warm-up
            v = vals + np.float32(1e-6) * rep  # distinct inputs per rep
            t0 = time.perf_counter()
            out = segment_pixels_sharded(years_np, v, mask, params, mesh)
            jax.block_until_ready(out.rmse)
            dt = time.perf_counter() - t0
            if rep:  # summarize exercises the psum-shaped reduction once
                best = min(best, dt)
        summarize_sharded(out)
        return best

    t_n = run_on(list(devs[:n_dev]))
    t_1 = run_on([devs[0]])
    rate_n = px / t_n
    scaling = t_1 / t_n
    extra = {
        "px": px,
        "mesh_devices": n_dev,
        "device_platform": devs[0].platform,
        "mode": "mesh-scaling",
        "t_mesh_s": round(t_n, 4),
        "t_single_s": round(t_1, 4),
        "px_per_s_total": round(rate_n, 1),
        "px_per_s_per_device": round(rate_n / n_dev, 1),
        "scaling_vs_single": round(scaling, 3),
        "scaling_efficiency": round(scaling / n_dev, 3),
        "note": (
            "virtual mesh on this host (no multi-chip hardware in the "
            "build environment): exercises the real sharding path + "
            "per-device bookkeeping. The N virtual devices SHARE the "
            "host's physical cores, so scaling_vs_single ~= 1 is the "
            "EXPECTED result (XLA already used every core in the "
            "single-device run); the pass criterion is mechanics (mesh "
            "build, sharded placement, SPMD compile, psum summary) plus "
            "a ratio that does not DEGRADE much below 1. Run on a real "
            "pod unchanged for hardware numbers."
        ),
    }
    rec = json.loads(_result_line(ny, rate_n / n_dev, extra=extra))
    rec["metric"] += f"_mesh{n_dev}"
    print(json.dumps(rec), flush=True)
    return 0


def main() -> int:
    """Parent: run the measurement in a child with retries + watchdog."""
    ny = int(os.environ.get("LT_BENCH_YEARS", 40))
    attempts = int(os.environ.get("LT_BENCH_ATTEMPTS", 4))
    timeout = float(os.environ.get("LT_BENCH_TIMEOUT", 900))
    env = dict(os.environ, LT_BENCH_CHILD="1")

    failures: list[str] = []
    for attempt in range(attempts):
        if attempt:
            backoff = min(15 * (2 ** (attempt - 1)), 120)
            print(
                f"bench: attempt {attempt} failed; retrying in {backoff}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(backoff)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired as e:
            # keep the child's progress lines — they localize the hang
            # (init vs compile vs run)
            tail = ""
            if e.stderr:
                err_text = (
                    e.stderr.decode(errors="replace")
                    if isinstance(e.stderr, bytes)
                    else e.stderr
                )
                sys.stderr.write(err_text)
                tail = " | ".join(err_text.strip().splitlines()[-2:])
            failures.append(
                f"attempt {attempt + 1}: killed after {timeout:.0f}s {tail}"
            )
            continue
        sys.stderr.write(proc.stderr)
        if proc.returncode == 0:
            # forward exactly the child's one JSON line
            for line in proc.stdout.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    print(line, flush=True)
                    return 0
            failures.append(f"attempt {attempt + 1}: rc=0 but no JSON line")
            continue
        if proc.returncode == _EXIT_INIT_HANG:
            failures.append(f"attempt {attempt + 1}: backend init hang (watchdog)")
            continue
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        failures.append(f"attempt {attempt + 1}: rc={proc.returncode} {' | '.join(tail)}")
        # UNAVAILABLE / init errors were observed to be transient — retry all

    print(_result_line(ny, 0.0, error="; ".join(failures)), flush=True)
    return 1


if __name__ == "__main__":
    if "--mesh" in sys.argv:
        try:
            _n = int(sys.argv[sys.argv.index("--mesh") + 1])
            if _n < 1:
                raise ValueError(_n)
        except (IndexError, ValueError):
            # honor the one-JSON-line contract even for bad argv
            print(_result_line(
                int(os.environ.get("LT_BENCH_YEARS", 40)), 0.0,
                error="--mesh requires a positive integer device count",
            ), flush=True)
            sys.exit(2)
        if os.environ.get("LT_BENCH_MESH_CHILD") == "1":
            sys.exit(_mesh_main(_n))
        # env must be set BEFORE jax initialises its backends: re-exec
        _env = dict(
            os.environ,
            LT_BENCH_MESH_CHILD="1",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={_n}"
            ).strip(),
        )
        _proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh", str(_n)],
            env=_env,
        )
        sys.exit(_proc.returncode)
    if os.environ.get("LT_BENCH_CHILD") == "1":
        sys.exit(_child_main())
    sys.exit(main())
