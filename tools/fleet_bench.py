"""Serving-fleet bench: a heavy-tailed multi-tenant trace, 1 vs N replicas.

The headline numbers of the fleet layer (ISSUE 13): replay ONE fixed
multi-tenant job trace — three tenants with skewed demand, two program
shapes, a heavy tail of 4x-pixel jobs — through a real
:class:`~land_trendr_tpu.fleet.router.FleetRouter` over real spawned
``lt serve`` replica processes, in four legs:

* **single** — one replica (the PR-7 baseline a fleet must beat);
* **noaff** — N replicas, warm-affinity OFF (pure least-loaded): shapes
  bounce between replicas, so each replica compiles each shape;
* **affinity** — N replicas, warm-affinity ON: repeat shapes stick to
  the replica already holding the compiled program;
* **kill** — the affinity configuration with one replica SIGKILLed
  mid-trace: the router re-routes its jobs (router-pinned workdirs
  resume on the survivor) and NOTHING is lost.

Per leg: client-side p50/p99 latency, the fleet-wide **warm-hit ratio**
(program-cache hits / lookups summed over every job's run), per-tenant
throughput and its spread (fairness), re-route and loss counts.  The
exact invariants ``tools/perf_gate.py``'s router leg gates:

* affinity's warm-hit ratio strictly above the no-affinity baseline;
* ZERO lost jobs across the replica kill (every job terminal ``done``,
  at least one re-routed);
* artifacts byte-identical for the same job spec across ALL legs
  (routing is a pure execution strategy, never a numerics change).

    python tools/fleet_bench.py --smoke --out /tmp/fleet_smoke.json
    python tools/fleet_bench.py --out FLEETSERVE_r14.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import shutil
import signal
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402

#: the two program shapes (different params → different compiled
#: programs → different affinity keys)
_SHAPES = {
    "a": {"max_segments": 4, "vertex_count_overshoot": 2},
    "b": {"max_segments": 6, "vertex_count_overshoot": 2},
}


def _digest_workdir(workdir: str) -> dict:
    """tile_id → {array name → sha256} (array-content identity — the
    fault_soak/serve_bench discipline)."""
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


def _percentile(vals: list, q: float) -> "float | None":
    if not vals:
        return None
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return round(s[idx], 4)


def build_trace(smoke: bool) -> list:
    """The FIXED multi-tenant trace: ``(tenant, shape, scene)`` tuples
    in submission order.  Tenant ``agency`` is the heavy tenant (most
    jobs), ``alerts`` and ``research`` are light; scene ``big`` (4x the
    pixels of ``small``) is the heavy tail — rare but latency-dominant.
    Deterministic by construction: every leg replays the SAME list.
    """
    base = [
        ("agency", "a", "small"),
        ("agency", "a", "small"),
        ("alerts", "b", "small"),
        ("agency", "a", "small"),
        ("research", "b", "small"),
        ("agency", "a", "big"),
        ("agency", "a", "small"),
        ("alerts", "b", "small"),
        ("agency", "b", "small"),
        ("agency", "a", "small"),
        ("research", "b", "big"),
        ("agency", "a", "small"),
    ]
    if smoke:
        return base
    return base + [
        ("agency", "a", "small"),
        ("alerts", "b", "small"),
        ("agency", "b", "small"),
        ("agency", "a", "small"),
        ("research", "a", "small"),
        ("agency", "a", "big"),
        ("alerts", "b", "small"),
        ("agency", "a", "small"),
    ]


def _write_scenes(root: Path, size: int, years: int) -> dict:
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack

    scenes = {}
    for name, edge in (("small", size), ("big", size * 2)):
        d = str(root / f"stack_{name}")
        write_stack(
            d,
            make_stack(
                SceneSpec(
                    width=edge, height=edge, year_start=2000,
                    year_end=2000 + years - 1, seed=13,
                )
            ),
        )
        scenes[name] = d
    return scenes


def _job_payload(scenes: dict, tenant: str, shape: str, scene: str,
                 tile: int) -> dict:
    return {
        "stack_dir": scenes[scene],
        "tile_size": tile,
        "tenant": tenant,
        "params": dict(_SHAPES[shape]),
        "run_overrides": {"retry_backoff_s": 0.0},
    }


def run_leg(
    name: str,
    root: Path,
    scenes: dict,
    trace: list,
    tile: int,
    n_replicas: int,
    affinity: bool,
    kill_one: bool = False,
    timeout_s: float = 900.0,
) -> dict:
    """One leg: fresh router + fresh replica processes (honest compile
    counts), the whole trace submitted as a burst, every job awaited to
    terminal.  ``kill_one`` SIGKILLs the busiest replica once the trace
    is in flight — the zero-lost-jobs leg."""
    from land_trendr_tpu.fleet import FleetRouter, RouterConfig

    cfg = RouterConfig(
        workdir=str(root / f"rt_{name}"),
        spawn_replicas=n_replicas,
        affinity=affinity,
        health_interval_s=0.5,
        route_queue_depth=256,
        tenant_quota=64,
        route_retries=3,
        replica_args=("--feed-cache-mb", "64"),
    )
    router = FleetRouter(cfg)
    thread = threading.Thread(
        target=router.serve_forever, name=f"fleet-bench-{name}"
    )
    thread.start()
    killed_rid = None
    # CLOSED-LOOP replay: at most ``max_out`` jobs outstanding — the
    # steady-arrival pattern a serving fleet actually sees (an
    # unbounded burst saturates every replica instantly, and spilling
    # past the warm replica is then the CORRECT routing choice — it
    # would measure the admission policy, not the affinity policy)
    max_out = n_replicas + 1
    kill_at = len(trace) // 3 if kill_one else None
    try:
        t0 = time.perf_counter()
        submits: list = []
        results: dict = {}
        pending: set = set()
        deadline = time.monotonic() + timeout_s

        def _drain(block_below: int) -> None:
            while len(pending) > block_below:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"leg {name}: {len(pending)} job(s) not "
                        f"terminal in {timeout_s}s"
                    )
                done_now = []
                for jid in sorted(pending):
                    s = router.job_status(jid)
                    if s and s["state"] not in ("queued", "routed"):
                        results[jid] = (s, time.perf_counter())
                        done_now.append(jid)
                pending.difference_update(done_now)
                if len(pending) > block_below:
                    time.sleep(0.05)

        for idx, (tenant, shape, scene) in enumerate(trace):
            _drain(max_out - 1)
            snap = router.submit(
                _job_payload(scenes, tenant, shape, scene, tile)
            )
            submits.append((snap["job_id"], tenant, shape, scene,
                            time.perf_counter()))
            pending.add(snap["job_id"])
            if kill_at is not None and idx == kill_at:
                # kill the replica holding in-flight work mid-trace
                victim = None
                while time.monotonic() < deadline and victim is None:
                    with router._lock:
                        busy = sorted(
                            (r for r in router.pool
                             if r.spawned and r.inflight
                             and r.proc is not None
                             and r.proc.poll() is None),
                            key=lambda r: -len(r.inflight),
                        )
                        victim = busy[0] if busy else None
                    if victim is None:
                        time.sleep(0.05)
                if victim is None:
                    raise RuntimeError(
                        "kill leg: no replica ever held a job"
                    )
                killed_rid = victim.rid
                victim.proc.send_signal(signal.SIGKILL)
                kill_at = None
        _drain(0)
        wall_s = time.perf_counter() - t0
    finally:
        router.stop()
        thread.join(timeout=300)

    # -- fold the leg ------------------------------------------------------
    latencies: list = []
    per_tenant: dict = {}
    hits = lookups = 0
    lost = rerouted = 0
    digests: dict = {}
    for jid, tenant, shape, scene, t_sub in submits:
        snap, t_done = results[jid]
        if snap["state"] != "done":
            lost += 1
            continue
        lat = t_done - t_sub
        latencies.append(lat)
        t = per_tenant.setdefault(
            tenant, {"jobs": 0, "latency_s": [], "first_t": t_sub,
                     "last_t": t_done},
        )
        t["jobs"] += 1
        t["latency_s"].append(lat)
        t["last_t"] = max(t["last_t"], t_done)
        if snap["attempts"] > 1:
            rerouted += 1
        pc = (snap.get("result") or {}).get("summary", {}).get(
            "program_cache"
        ) or {}
        hits += pc.get("hits", 0)
        lookups += pc.get("hits", 0) + pc.get("misses", 0)
        digests.setdefault((shape, scene), []).append(
            _digest_workdir(snap["workdir"])
        )
    tenants_out: dict = {}
    rates: list = []
    for tenant in sorted(per_tenant):
        t = per_tenant[tenant]
        span = max(1e-6, t["last_t"] - t["first_t"])
        rate = t["jobs"] / span
        rates.append(rate)
        tenants_out[tenant] = {
            "jobs": t["jobs"],
            "mean_latency_s": round(
                sum(t["latency_s"]) / len(t["latency_s"]), 4
            ),
            "jobs_per_s": round(rate, 4),
        }
    return {
        "replicas": n_replicas,
        "affinity": affinity,
        "jobs": len(submits),
        "lost_jobs": lost,
        "rerouted_jobs": rerouted,
        "killed_replica": killed_rid,
        "wall_s": round(wall_s, 3),
        "p50_latency_s": _percentile(latencies, 0.50),
        "p99_latency_s": _percentile(latencies, 0.99),
        "warm_hits": hits,
        "warm_lookups": lookups,
        "warm_hit_ratio": round(hits / lookups, 4) if lookups else None,
        "per_tenant": tenants_out,
        # per-tenant throughput spread: max/min jobs-per-second over
        # the tenants that ran — the fairness number (1.0 = perfectly
        # even service under the weights)
        "tenant_throughput_spread": (
            round(max(rates) / min(rates), 3) if rates and min(rates) > 0
            else None
        ),
        "_digests": digests,
    }


def run_bench(
    smoke: bool, root: str, size: int, years: int, tile: int,
    n_replicas: int,
) -> dict:
    rootp = Path(root)
    scenes = _write_scenes(rootp, size, years)
    trace = build_trace(smoke)
    legs: dict = {}
    legs["single"] = run_leg(
        "single", rootp, scenes, trace, tile, 1, affinity=True
    )
    legs["noaff"] = run_leg(
        "noaff", rootp, scenes, trace, tile, n_replicas, affinity=False
    )
    legs["affinity"] = run_leg(
        "affinity", rootp, scenes, trace, tile, n_replicas, affinity=True
    )
    legs["kill"] = run_leg(
        "kill", rootp, scenes, trace, tile, n_replicas, affinity=True,
        kill_one=True,
    )

    # cross-leg artifact parity: the same (shape, scene) spec must
    # produce byte-identical tile arrays in EVERY leg — kill included
    parity_ok = True
    ref: dict = {}
    for leg in legs.values():
        for spec, dlist in leg.pop("_digests").items():
            for d in dlist:
                if not d:
                    parity_ok = False
                    continue
                if spec not in ref:
                    ref[spec] = d
                elif ref[spec] != d:
                    parity_ok = False

    kill = legs["kill"]
    invariants = {
        "affinity_warm_above_noaff": bool(
            legs["affinity"]["warm_hit_ratio"] is not None
            and legs["noaff"]["warm_hit_ratio"] is not None
            and legs["affinity"]["warm_hit_ratio"]
            > legs["noaff"]["warm_hit_ratio"]
        ),
        "zero_lost_jobs_across_kill": bool(
            kill["lost_jobs"] == 0 and kill["rerouted_jobs"] >= 1
            and kill["killed_replica"] is not None
        ),
        "no_leg_lost_jobs": all(
            leg["lost_jobs"] == 0 for leg in legs.values()
        ),
        "parity_across_legs": bool(parity_ok and ref),
    }
    return {
        "workload": {
            "smoke": smoke,
            "jobs": len(trace),
            "tenants": sorted({t for t, _, _ in trace}),
            "shapes": sorted({s for _, s, _ in trace}),
            "scene_small_px": size * size,
            "scene_big_px": (size * 2) ** 2,
            "years": years,
            "tile_size": tile,
            "replicas": n_replicas,
        },
        "legs": legs,
        "invariants": invariants,
        "ok": all(invariants.values()),
    }


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="minutes-scale gate mode (short trace, tiny "
                    "scenes)")
    ap.add_argument("--size", type=int, default=None,
                    help="small-scene edge px (default: 40 smoke / 64 "
                    "full; the big scene is 2x the edge)")
    ap.add_argument("--years", type=int, default=None,
                    help="stack years (default: 7 smoke / 9 full)")
    ap.add_argument("--tile", type=int, default=None,
                    help="tile size (default: 20 smoke / 32 full)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="fleet size N for the multi-replica legs")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the bench workdirs under DIR")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    size = args.size or (40 if args.smoke else 64)
    years = args.years or (7 if args.smoke else 9)
    tile = args.tile or (20 if args.smoke else 32)

    root = args.keep or tempfile.mkdtemp(prefix="lt_fleet_bench_")
    Path(root).mkdir(parents=True, exist_ok=True)
    try:
        report = run_bench(
            args.smoke, root, size, years, tile, args.replicas
        )
    finally:
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(
        json.dumps(
            {
                "ok": report["ok"],
                "p99_single_s": report["legs"]["single"]["p99_latency_s"],
                "p99_noaff_s": report["legs"]["noaff"]["p99_latency_s"],
                "p99_affinity_s": report["legs"]["affinity"]["p99_latency_s"],
                "warm_noaff": report["legs"]["noaff"]["warm_hit_ratio"],
                "warm_affinity": report["legs"]["affinity"]["warm_hit_ratio"],
                "kill_rerouted": report["legs"]["kill"]["rerouted_jobs"],
                "invariants": report["invariants"],
            }
        )
    )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
