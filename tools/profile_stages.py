"""Per-stage kernel time attribution from a JAX profiler trace.

VERDICT r2 next-round item #7: commit a CPU stage-share profile so the
kernel-efficiency question ("does ``_despike``'s fixed-NY loop or
``_find_candidates``' membership recompute dominate?") is answered with a
measurement instead of a guess.  Not a TPU substitute — a slack-finder.

How it works (the named_scope → trace join):

1. compile the kernel for the profiled shape and parse the *optimized* HLO
   text: every instruction line carries ``metadata={op_name="...
   lt_<stage>..."}``, giving an instruction-name → stage map that survives
   XLA fusion (fusions inherit their root's op_name);
2. run :func:`land_trendr_tpu.utils.profiling.profile_op` (warm-up
   excluded, N steady-state iterations) and parse the resulting
   ``*.xplane.pb`` with a minimal vendored schema mirror
   (``tools/_proto/lt_xplane.proto`` — the tensorboard plugin's generated
   protos are incompatible with this environment's protobuf);
3. trace spans nest (a ``while`` thunk contains its body's fusion spans),
   so per-event SELF time is computed with an interval stack before
   aggregating by stage — no double counting;
4. stage shares are reported over kernel-attributed self time; runtime /
   scheduler spans (ThunkExecutor etc.) are reported separately.

Usage: python tools/profile_stages.py [px] [out.json] [--platform=cpu]
"""

from __future__ import annotations

import collections
import glob
import json
import os
import re
import sys
import tempfile

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "_proto"))

from _platform_arg import pop_platform_arg  # noqa: E402

jax.config.update("jax_platforms", pop_platform_arg())

from land_trendr_tpu.utils.compilation_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()


def build_scope_map(hlo_text: str, scopes: tuple[str, ...]) -> dict[str, str]:
    """instruction name → first matching lt_* scope in its op_name."""
    out: dict[str, str] = {}
    inst = re.compile(r"%?([\w.-]+)\s*=")
    opname = re.compile(r'op_name="([^"]*)"')
    for line in hlo_text.splitlines():
        o = opname.search(line)
        if not o:
            continue
        m = inst.search(line)
        if not m:
            continue
        for s in scopes:
            if s in o.group(1):
                out[m.group(1)] = s
                break
    return out


def self_times(plane) -> dict[str, float]:
    """Event-name → self seconds across all lines, nesting-aware."""
    acc: collections.Counter[str] = collections.Counter()
    for line in plane.lines:
        evs = sorted(
            (
                (ev.offset_ps, ev.duration_ps, plane.event_metadata[ev.metadata_id].name)
                for ev in line.events
                if not plane.event_metadata[ev.metadata_id].name.startswith("end:")
            ),
            key=lambda t: (t[0], -t[1]),
        )
        stack: list[list] = []  # [end_ps, name, self_ps]
        for off, dur, name in evs:
            while stack and stack[-1][0] <= off:
                end, n, s = stack.pop()
                acc[n] += s
            if stack:
                stack[-1][2] -= dur  # child time is not parent self time
            stack.append([off + dur, name, dur])
        while stack:
            end, n, s = stack.pop()
            acc[n] += s
    return {k: v / 1e12 for k, v in acc.items()}


def main() -> int:
    px = int(sys.argv[1]) if len(sys.argv) > 1 else 65_536
    out_path = sys.argv[2] if len(sys.argv) > 2 else "PROFILE_r03.json"
    iters = int(os.environ.get("LT_PROFILE_ITERS", 3))

    import numpy as np

    from bench import make_series
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels
    from land_trendr_tpu.utils.profiling import STAGE_SCOPES, profile_op

    import lt_xplane_pb2

    params = LTParams()
    years, vals, mask = make_series(px, 40)

    print(f"profile_stages: compiling for px={px} ...", file=sys.stderr, flush=True)
    compiled = jax.jit(jax_segment_pixels, static_argnums=3).lower(
        years, vals, mask, params
    ).compile()
    if os.environ.get("LT_PROFILE_DUMP_HLO"):
        # the optimized HLO the Pallas decision rule inspects for layout/
        # copy/transpose fusions (ops/segment.py "TPU-profile trigger")
        from tools._measure import write_text_atomic

        write_text_atomic(out_path + ".hlo.txt", compiled.as_text())
        print(f"profile_stages: HLO dumped to {out_path}.hlo.txt", file=sys.stderr)
    scope_map = build_scope_map(compiled.as_text(), tuple(STAGE_SCOPES))
    print(
        f"profile_stages: {len(scope_map)} instructions mapped to stages",
        file=sys.stderr,
        flush=True,
    )

    logdir = tempfile.mkdtemp(prefix="lt_profile_")
    r = profile_op(jax_segment_pixels, years, vals, mask, params, logdir=logdir, iters=iters)

    pbs = sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime,
    )
    if not pbs:
        sys.exit(f"no xplane.pb under {logdir}")
    xs = lt_xplane_pb2.XSpace()
    with open(pbs[-1], "rb") as f:
        xs.ParseFromString(f.read())

    stage_s: collections.Counter[str] = collections.Counter()
    runtime_s = 0.0
    unmapped: collections.Counter[str] = collections.Counter()
    for plane in xs.planes:
        if not plane.lines:
            continue
        for name, secs in self_times(plane).items():
            if name in scope_map:
                stage_s[scope_map[name]] += secs
            elif re.match(r"[\w-]+(\.\d+)?$", name) and (
                "fusion" in name
                or name.startswith(("while", "wrapped_", "copy", "bitcast", "convert"))
            ):
                unmapped[name] += secs
            else:
                runtime_s += secs

    kernel_total = sum(stage_s.values())
    unmapped_total = sum(unmapped.values())
    if kernel_total == 0.0:
        # a backend whose trace event names don't match HLO instruction
        # names (or an XLA that drops op_name) yields zero attribution —
        # report it as a diagnostic instead of dividing by zero after the
        # expensive profile run
        print(
            "profile_stages: WARNING — no trace event mapped to any stage; "
            "shares unavailable on this backend",
            file=sys.stderr,
            flush=True,
        )
    record = {
        "n_pixels": px,
        "n_years": 40,
        "platform": jax.devices()[0].platform,
        "iters": iters,
        "wall_s_per_iter": round(r["wall_s_per_iter"], 4),
        "pixels_per_sec": round(px / r["wall_s_per_iter"], 1),
        "stage_share": {
            k: round(v / kernel_total, 4) for k, v in stage_s.most_common()
        } if kernel_total > 0.0 else None,
        "stage_self_s_total": {
            k: round(v, 4) for k, v in stage_s.most_common()
        },
        "kernel_attributed_s": round(kernel_total, 4),
        "unmapped_kernel_s": round(unmapped_total, 4),
        "unmapped_top": {
            k: round(v, 4) for k, v in unmapped.most_common(5)
        },
        "runtime_overhead_s": round(runtime_s, 4),
    }
    from tools._measure import write_json_atomic

    write_json_atomic(out_path, record)
    print(json.dumps(record, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
