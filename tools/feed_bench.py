"""Feed-path decode benchmark: decoded-block cache + parallel block decode.

Builds a synthetic **tiled-deflate** scene (one single-band uint16 GeoTIFF
per year — the shape the C2 lazy ingest feeds from) and measures the
window-read feed stage three ways over the same row-major tile sweep:

* ``baseline`` — ``feed_cache_mb=0``, ``decode_workers=1``: the serial,
  uncached pre-cache feed path;
* ``parallel`` — cache still off, ``decode_workers`` threads: thread
  scaling alone;
* ``cached``   — cache + parallel decode (``RunConfig.feed_cache_mb`` /
  ``decode_workers``): the acceptance comparison;
* ``cached_readahead`` — cache + parallel + next-window hints.  Recorded
  for completeness: in this HOST-ONLY loop there is no device wait to
  overlap, so on small hosts the hint work competes with the main loop —
  the driver issues hints from its feed pool while the device computes,
  which is where readahead actually pays.

The tile windows deliberately misalign with the 256-px TIFF block grid,
so adjacent windows straddle compressed blocks — the revisit pattern the
r05 gigapixel run's feed stage paid for serially (GIGA_r05.json
``stage_s``: feed 18.96s of 56.9s wall).  Byte-identity of cached vs
uncached reads is asserted on sampled windows every run.

Writes one JSON artifact (``--out``, e.g. ``FEED_r07.json``) and, with
``--events-dir``, a schema-valid ``events.jsonl`` through the obs
Telemetry (``run_start`` / ``feed_cache`` / ``run_done``) so
``tools/obs_report.py`` surfaces the cache and decode-seconds counters.

``--smoke`` shrinks the scene to seconds-not-minutes scale — the tier-1
``-m 'not slow'`` mode ``tests/test_feed_cache.py`` runs in CI.

Usage:
    python tools/feed_bench.py --out FEED_r07.json
    python tools/feed_bench.py --smoke --out /tmp/feed_smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402

from land_trendr_tpu.io import blockcache, native  # noqa: E402
from land_trendr_tpu.io.geotiff import (  # noqa: E402
    read_geotiff,
    read_geotiff_window,
    write_geotiff,
)


def build_scene(scene_dir: str, size: int, years: int, seed: int) -> list[str]:
    """One tiled-deflate uint16 single-band file per year (256-px blocks,
    predictor on — the layout the stream writer and C2 products use).
    Smooth ramps + noise so deflate genuinely compresses (and inflate
    genuinely costs — all-random data would be stored, not deflated)."""
    os.makedirs(scene_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:size, 0:size]
    base = (yy * 3 + xx * 2) % 4096
    paths = []
    for k in range(years):
        arr = (
            base + k * 37 + rng.integers(0, 64, size=(size, size))
        ).astype(np.uint16)
        p = os.path.join(scene_dir, f"band_{1984 + k}.tif")
        write_geotiff(p, arr, compress="deflate", tile=256, predictor=True)
        paths.append(p)
    return paths


def plan_windows(size: int, window: int) -> list[tuple[int, int, int, int]]:
    wins = []
    for y0 in range(0, size, window):
        for x0 in range(0, size, window):
            wins.append((y0, x0, min(window, size - y0), min(window, size - x0)))
    return wins


def sweep(
    paths: list[str],
    wins: list[tuple[int, int, int, int]],
    readahead: bool,
) -> float:
    """One feed pass: every window of every year, row-major — the access
    pattern of the driver's lazy tile feed.  With ``readahead``, the next
    window's blocks are hinted before the current one decodes (the driver
    does this from the feed pool while the device computes)."""
    t0 = time.perf_counter()
    for wi, win in enumerate(wins):
        if readahead and wi + 1 < len(wins):
            nxt = wins[wi + 1]
            for p in paths:
                blockcache.prefetch_window(p, *nxt)
        for p in paths:
            read_geotiff_window(p, *win)
    return time.perf_counter() - t0


def check_parity(paths: list[str], wins, n_sample: int = 4) -> int:
    """Assert cached window reads byte-match the full-read reference on a
    sample of windows (the current blockcache configuration applies)."""
    full = {p: read_geotiff(p)[0] for p in paths[:2]}
    step = max(1, len(wins) // n_sample)
    checked = 0
    for win in wins[::step]:
        y0, x0, h, w = win
        for p, ref in full.items():
            got = read_geotiff_window(p, *win)
            if not np.array_equal(got, ref[y0 : y0 + h, x0 : x0 + w]):
                raise AssertionError(f"window {win} of {p} mismatches full read")
            checked += 1
    return checked


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--size", type=int, default=2048, help="scene edge (px)")
    ap.add_argument("--years", type=int, default=6, help="files in the stack")
    ap.add_argument("--window", type=int, default=192,
                    help="feed window edge; deliberately NOT a multiple of "
                    "the 256-px TIFF block, so windows straddle blocks")
    ap.add_argument("--cache-mb", type=int, default=256)
    ap.add_argument("--workers", type=int, default=0, help="0 = auto")
    ap.add_argument("--seed", type=int, default=20260802)
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per mode; the MEDIAN wall is "
                    "reported (this 2-core container's scheduler noise is "
                    "large relative to a single pass)")
    ap.add_argument("--out", default="FEED_r07.json")
    ap.add_argument("--scene-dir", default=None,
                    help="keep/reuse the scene here (default: a temp dir)")
    ap.add_argument("--events-dir", default=None,
                    help="also emit a schema-valid events.jsonl with the "
                    "feed_cache rollup (fold with tools/obs_report.py)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny scene, seconds not minutes (tier-1 CI mode)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.size = min(args.size, 512)
        args.years = min(args.years, 3)
        args.window = min(args.window, 160)
        args.reps = 1

    tmp = None
    scene_dir = args.scene_dir
    if scene_dir is None:
        tmp = tempfile.mkdtemp(prefix="lt_feed_bench_")
        scene_dir = tmp
    try:
        paths = build_scene(scene_dir, args.size, args.years, args.seed)
        wins = plan_windows(args.size, args.window)
        px = args.size * args.size * args.years

        def run(cache_mb: int, workers: int, readahead: bool) -> dict:
            blockcache.configure(
                budget_bytes=cache_mb << 20, workers=workers
            )
            walls = []
            stats = None
            for _ in range(max(1, args.reps)):
                blockcache.cache_clear()  # every rep decodes from cold
                base = blockcache.stats_snapshot()
                walls.append(sweep(paths, wins, readahead=readahead))
                stats = blockcache.stats_delta(base)
            wall = sorted(walls)[len(walls) // 2]  # median
            return {
                "wall_s": round(wall, 4),
                "px_per_s": round(px / wall, 1),
                "walls_s": [round(w, 4) for w in walls],
                "stats": stats,
            }

        # untimed warmup: fault the scene into the page cache so the first
        # timed mode does not pay cold-file I/O the others never see
        blockcache.configure(0, 1)
        sweep(paths, wins, readahead=False)

        baseline = run(0, 1, readahead=False)
        parallel = run(0, args.workers, readahead=False)
        cached = run(args.cache_mb, args.workers, readahead=False)
        cached_ra = run(args.cache_mb, args.workers, readahead=True)
        # parity under the CACHED configuration (hits served from cache)
        parity_checked = check_parity(paths, wins)

        result = {
            "scene": {
                "size": args.size,
                "years": args.years,
                "window": args.window,
                "layout": "tiled-256 deflate+predictor uint16",
                "windows": len(wins),
                "pixels": px,
            },
            "config": {
                "cache_mb": args.cache_mb,
                "decode_workers": args.workers,
                "cpu_count": os.cpu_count(),
                "native": native.available(),
            },
            "baseline_serial_uncached": {
                k: baseline[k] for k in ("wall_s", "px_per_s")
            },
            "parallel_uncached": {
                k: parallel[k] for k in ("wall_s", "px_per_s")
            },
            "cached_parallel": {
                k: cached[k] for k in ("wall_s", "px_per_s")
            },
            "cached_parallel_readahead": {
                k: cached_ra[k] for k in ("wall_s", "px_per_s")
            },
            "speedup_parallel": round(
                baseline["wall_s"] / parallel["wall_s"], 3
            ),
            "speedup_cached": round(baseline["wall_s"] / cached["wall_s"], 3),
            "cache_stats": cached["stats"],
            "readahead_stats": {
                k: cached_ra["stats"][k]
                for k in ("readahead_blocks", "readahead_hits",
                          "readahead_dropped", "hits", "misses")
            },
            "parity_windows_checked": parity_checked,
            "parity_ok": True,
        }
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, result)

        if args.events_dir:
            from land_trendr_tpu.obs import Telemetry

            tel = Telemetry(args.events_dir, fingerprint="feed_bench")
            try:
                tel.run_start(
                    fingerprint="feed_bench",
                    process_index=0,
                    process_count=1,
                    tiles_total=len(wins),
                    tiles_todo=len(wins),
                    tiles_skipped_resume=0,
                    mesh_devices=1,
                    impl="host-feed",
                )
                tel.feed_cache(cached["stats"])
                tel.run_done(
                    "ok",
                    tiles_done=len(wins),
                    pixels=px,
                    wall_s=cached["wall_s"],
                    px_per_s=cached["px_per_s"],
                    fit_rate=0.0,
                )
            finally:
                tel.close()

        print(json.dumps(result, indent=2))
        return 0
    finally:
        blockcache.configure(0, None)  # leave the process unconfigured
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
