"""On-chip XLA-vs-Pallas implementation identity proof (committed form).

Runs BOTH compiled f32 kernels — the portable XLA kernel and the fused
Pallas kernel — over the identical parity-suite population on the real
TPU and measures per-pixel decision overlap directly.  This is the
auditable form of the "identical parity taxonomy" observation in
``PARITY_f32_tpu*.json``: if the two implementations are bit-identical
pixel-for-pixel, every oracle disagreement belongs to both.

Round-5 contract update: with the tail fused into the Pallas kernel, all
DECISION fields and float trajectories remain bit-identical, but
``p_of_f`` is evaluated by the same Lentz expression in two different
fusion contexts (Mosaic in-kernel vs the XLA tail), whose last-ulp
rounding differs — the artifact therefore records its max relative delta
(expected within the documented Lentz envelope, ~1e-4) instead of
asserting bitwise equality on it.  Same principle as the f64 suite
(``tests/test_pallas.py::_assert_outputs_equal``).

Usage::  python tools/impl_identity.py [--px 1048576] [--out IMPL_IDENTITY_rNN.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--px", type=int, default=1048576)
    ap.add_argument("--chunk", type=int, default=262144)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    import jax

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels_chunked
    from land_trendr_tpu.ops.segment_pallas import (
        jax_segment_pixels_pallas_chunked,
    )
    from land_trendr_tpu.utils.compilation_cache import enable_persistent_cache
    from tools._population import make_population

    enable_persistent_cache()
    params = LTParams()
    px, ny = args.px, 40
    n_seeds = 16
    per = px // n_seeds
    args.chunk = min(args.chunk, per)
    platform = jax.default_backend()

    stats = {
        "pixel_exact_vertex_indices": 0,
        "model_valid_equal": 0,
        "n_vertices_equal": 0,
        "fitted_abs_delta_max": 0.0,
        "p_of_f_rel_delta_max": 0.0,
    }
    done = 0
    for seed in range(n_seeds):
        rng = np.random.default_rng(seed)
        years, vals, mask = make_population(rng, per, ny)
        vals = vals.astype(np.float32)
        out_x = jax.block_until_ready(
            jax_segment_pixels_chunked(years, vals, mask, params, args.chunk)
        )
        out_p = jax.block_until_ready(
            jax_segment_pixels_pallas_chunked(
                years, vals, mask, params, chunk=args.chunk
            )
        )
        vi_eq = np.all(
            np.asarray(out_x.vertex_indices) == np.asarray(out_p.vertex_indices),
            axis=1,
        )
        stats["pixel_exact_vertex_indices"] += int(vi_eq.sum())
        stats["model_valid_equal"] += int(
            (np.asarray(out_x.model_valid) == np.asarray(out_p.model_valid)).sum()
        )
        stats["n_vertices_equal"] += int(
            (np.asarray(out_x.n_vertices) == np.asarray(out_p.n_vertices)).sum()
        )
        stats["fitted_abs_delta_max"] = max(
            stats["fitted_abs_delta_max"],
            float(
                np.max(
                    np.abs(
                        np.asarray(out_x.fitted, np.float64)
                        - np.asarray(out_p.fitted, np.float64)
                    )
                )
            ),
        )
        px_ = np.asarray(out_x.p_of_f, np.float64)
        pp_ = np.asarray(out_p.p_of_f, np.float64)
        stats["p_of_f_rel_delta_max"] = max(
            stats["p_of_f_rel_delta_max"],
            float(np.max(np.abs(px_ - pp_) / np.maximum(np.abs(px_), 1e-30))),
        )
        done += per
        print(f"seed {seed}: cumulative exact "
              f"{stats['pixel_exact_vertex_indices']}/{done}", flush=True)

    out = {
        "n_pixels": done,
        "platform": f"{platform} (both legs, same chip)",
        "population": "tools/_population.make_population seeds 0-15 "
                      "(the parity-suite population)",
        **{k: (round(v, 12) if isinstance(v, float) else v)
           for k, v in stats.items()},
        "pixel_exact_rate": stats["pixel_exact_vertex_indices"] / done,
        "note": "XLA kernel vs round-5 FUSED Pallas kernel, both compiled "
                "f32 on the same chip over identical inputs.  Decisions and "
                "trajectories compared bitwise; p_of_f compared by relative "
                "delta (two fusion contexts of the same Lentz expression — "
                "see tools/impl_identity.py docstring).",
    }
    line = json.dumps(out, indent=1)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
