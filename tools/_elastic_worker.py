"""Standalone elastic-pod worker: one independent process, one shared workdir.

Run as: ``python tools/_elastic_worker.py CONFIG.json``.  Unlike
``tests/_driver_worker.py`` this worker joins NO ``jax.distributed``
cluster — elastic lease scheduling coordinates purely through the shared
filesystem manifest, so a "pod" here is any set of independent processes
pointed at one workdir, and a host can join a run that is already in
flight (the late-joiner leg of ``tools/elastic_soak.py``) or be SIGKILLed
without taking anyone else down (the kill leg).

``CONFIG.json``::

    {
      "workdir": ..., "out_dir": ...,
      "width": 80, "height": 80, "tile_size": 20, "seed": 11,
      "summary_path": ...,            # where the run summary JSON lands
      "run": { ... RunConfig overrides: lease_batch, lease_ttl_s,
               speculate, fault_schedule, telemetry, ... }
    }

The synthetic scene is deterministic in (width, height, seed), so every
worker — and the soak's clean reference run — feeds identical pixels.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax  # noqa: E402

# must beat any boot-hook platform pin before a backend is touched
jax.config.update("jax_platforms", "cpu")


def main() -> int:
    with open(sys.argv[1]) as f:
        cfg_json = json.load(f)

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import (
        RunConfig,
        run_stack,
        stack_from_synthetic,
    )

    spec = SceneSpec(
        width=int(cfg_json["width"]),
        height=int(cfg_json["height"]),
        year_start=1990,
        year_end=2013,
        seed=int(cfg_json.get("seed", 11)),
    )
    rs = stack_from_synthetic(make_stack(spec))
    run_kw = dict(cfg_json.get("run", {}))
    params = run_kw.pop("params", {"max_segments": 4, "vertex_count_overshoot": 2})
    cfg = RunConfig(
        params=LTParams.from_dict(params),
        tile_size=int(cfg_json["tile_size"]),
        workdir=cfg_json["workdir"],
        out_dir=cfg_json["out_dir"],
        retry_backoff_s=0.0,
        **run_kw,
    )
    summary = run_stack(rs, cfg)
    out = cfg_json.get("summary_path")
    if out:
        from tools._measure import write_json_atomic

        # the soak's poll loop reads this file the instant it appears;
        # rename-as-commit means it never reads a torn summary
        write_json_atomic(out, summary, indent=None, trailing_newline=False)
    return 0


if __name__ == "__main__":
    sys.exit(main())
