#!/bin/bash
# Opportunistic TPU bench: retry all round long, commit-ready artifact on
# first success (VERDICT r2 next-round item #1: "adapt to the environment
# instead of timing out against it").
#
# The axon chip comes and goes: rounds 1-2 it never initialized; round 3
# saw ONE ~20-min window (2026-07-30 05:14-05:35 UTC) in which the full
# kernel ran clean at <=131072 px, then returned to init hangs.  So poll
# DENSELY (5 min) with a moderate per-attempt budget; bench.py's chain
# mode + device-fault px backoff + the persistent compile cache
# (utils/compilation_cache.py — round-4 addition: compile work survives a
# mid-window fault, so a second attempt inside the same window starts at
# the timed reps) do the rest when a window opens.
#
# Round suffix via LT_ROUND (default 04) so the same script re-arms each
# round without edits.
cd /root/repo
R="${LT_ROUND:-04}"
LOG=/root/repo/BENCH_r${R}_attempts.log
OUT=/root/repo/BENCH_r${R}.json
for i in $(seq 1 200); do
  # cheap 120 s init probe first: during the init-hang regime a full bench
  # attempt blocks 15-30 min before its watchdog fires, which would lower
  # the real poll cadence below the window length; only a probed-up
  # backend gets the full bench budget
  if ! timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1; then
    echo "[$(date -u +%FT%TZ)] probe $i: backend not up" >> "$LOG"
    sleep 300
    continue
  fi
  echo "[$(date -u +%FT%TZ)] attempt $i starting (probe green)" >> "$LOG"
  out=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1800 LT_BENCH_PX=65536 LT_BENCH_REPS=3 python bench.py 2>>"$LOG")
  echo "[$(date -u +%FT%TZ)] attempt $i result: $out" >> "$LOG"
  # accept only a real accelerator measurement: value > 0 AND the record's
  # device_platform is not cpu (the axon plugin can fail init and fall
  # back to the cpu backend, which must not become the artifact)
  val=$(echo "$out" | python -c "
import sys, json
r = json.loads(sys.stdin.readline())
print(r['value'] if r.get('device_platform') not in (None, 'cpu') else 0.0)
" 2>/dev/null)
  if [ -n "$val" ] && [ "$val" != "0.0" ] && [ "$val" != "0" ]; then
    echo "$out" > "$OUT"
    echo "[$(date -u +%FT%TZ)] SUCCESS — $OUT written (px=65536)" >> "$LOG"
    git -C /root/repo add "$OUT" >> "$LOG" 2>&1 && \
      git -C /root/repo commit -m "TPU bench artifact: 65536-px chain-mode number (watcher)" \
        -- "$OUT" >> "$LOG" 2>&1
    # while the window is open, also try the production 1M-px chunked
    # config; prefer it when it lands (px backoff inside bench.py keeps
    # this safe against the large-batch device faults)
    out2=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1800 LT_BENCH_REPS=3 \
           python bench.py 2>>"$LOG")
    echo "[$(date -u +%FT%TZ)] full-config attempt: $out2" >> "$LOG"
    val2=$(echo "$out2" | python -c "
import sys, json
r = json.loads(sys.stdin.readline())
print(r['value'] if r.get('device_platform') not in (None, 'cpu') else 0.0)
" 2>/dev/null)
    if [ -n "$val2" ] && [ "$val2" != "0.0" ] && [ "$val2" != "0" ]; then
      echo "$out2" > "$OUT"
      echo "[$(date -u +%FT%TZ)] $OUT upgraded to full config" >> "$LOG"
      git -C /root/repo add "$OUT" >> "$LOG" 2>&1 && \
        git -C /root/repo commit -m "TPU bench artifact: upgraded to 1M-px chunked config (watcher)" \
          -- "$OUT" >> "$LOG" 2>&1
    fi
    exit 0
  fi
  sleep 300
done
echo "[$(date -u +%FT%TZ)] exhausted all attempts without a TPU number" >> "$LOG"
exit 1
