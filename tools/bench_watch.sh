#!/bin/bash
# Opportunistic TPU bench: retry all round long, commit-ready artifact on
# first success (VERDICT r2 next-round item #1: "adapt to the environment
# instead of timing out against it").
cd /root/repo
LOG=/root/repo/BENCH_r03_attempts.log
for i in $(seq 1 40); do
  echo "[$(date -u +%FT%TZ)] attempt $i starting" >> "$LOG"
  out=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=3600 python bench.py 2>>"$LOG")
  echo "[$(date -u +%FT%TZ)] attempt $i result: $out" >> "$LOG"
  val=$(echo "$out" | python -c "import sys,json;print(json.loads(sys.stdin.readline())['value'])" 2>/dev/null)
  if [ -n "$val" ] && [ "$val" != "0.0" ] && [ "$val" != "0" ]; then
    echo "$out" > /root/repo/BENCH_r03.json
    echo "[$(date -u +%FT%TZ)] SUCCESS — BENCH_r03.json written" >> "$LOG"
    exit 0
  fi
  sleep 900
done
echo "[$(date -u +%FT%TZ)] exhausted all attempts without a TPU number" >> "$LOG"
exit 1
