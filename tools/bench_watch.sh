#!/bin/bash
# Opportunistic TPU bench: retry all round long, commit-ready artifact on
# first success (VERDICT r2 next-round item #1: "adapt to the environment
# instead of timing out against it").
#
# The axon chip comes and goes: rounds 1-2 it never initialized; on
# 2026-07-30 it opened a ~20-min window (05:14-05:35 UTC) in which the
# full kernel ran clean at <=131072 px, then returned to init hangs.
# So poll DENSELY (5 min) with a moderate per-attempt budget; bench.py's
# chain mode + device-fault px backoff does the rest when a window opens.
cd /root/repo
LOG=/root/repo/BENCH_r03_attempts.log
for i in $(seq 1 120); do
  # cheap 120 s init probe first: during the init-hang regime a full bench
  # attempt blocks 15-30 min before its watchdog fires, which would lower
  # the real poll cadence below the window length; only a probed-up
  # backend gets the full bench budget
  if ! timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1; then
    echo "[$(date -u +%FT%TZ)] probe $i: backend not up" >> "$LOG"
    sleep 300
    continue
  fi
  echo "[$(date -u +%FT%TZ)] attempt $i starting (probe green)" >> "$LOG"
  out=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1800 LT_BENCH_PX=65536 LT_BENCH_REPS=3 python bench.py 2>>"$LOG")
  echo "[$(date -u +%FT%TZ)] attempt $i result: $out" >> "$LOG"
  # accept only a real accelerator measurement: value > 0 AND the record's
  # device_platform is not cpu (the axon plugin can fail init and fall
  # back to the cpu backend, which must not become BENCH_r03.json)
  val=$(echo "$out" | python -c "
import sys, json
r = json.loads(sys.stdin.readline())
print(r['value'] if r.get('device_platform') not in (None, 'cpu') else 0.0)
" 2>/dev/null)
  if [ -n "$val" ] && [ "$val" != "0.0" ] && [ "$val" != "0" ]; then
    echo "$out" > /root/repo/BENCH_r03.json
    echo "[$(date -u +%FT%TZ)] SUCCESS — BENCH_r03.json written (px=65536)" >> "$LOG"
    # while the window is open, also try the production 1M-px chunked
    # config; prefer it when it lands (px backoff inside bench.py keeps
    # this safe against the large-batch device faults)
    out2=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1800 LT_BENCH_REPS=3 \
           python bench.py 2>>"$LOG")
    echo "[$(date -u +%FT%TZ)] full-config attempt: $out2" >> "$LOG"
    val2=$(echo "$out2" | python -c "
import sys, json
r = json.loads(sys.stdin.readline())
print(r['value'] if r.get('device_platform') not in (None, 'cpu') else 0.0)
" 2>/dev/null)
    if [ -n "$val2" ] && [ "$val2" != "0.0" ] && [ "$val2" != "0" ]; then
      echo "$out2" > /root/repo/BENCH_r03.json
      echo "[$(date -u +%FT%TZ)] BENCH_r03.json upgraded to full config" >> "$LOG"
    fi
    exit 0
  fi
  sleep 300
done
echo "[$(date -u +%FT%TZ)] exhausted all attempts without a TPU number" >> "$LOG"
exit 1
