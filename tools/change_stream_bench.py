"""Bounded-memory change maps at mosaic scale — the streamed downstream
layer's scale proof (companion to STREAMASM_r04.json's assembly proof).

Fabricates the full segment-product set (``ops/change._REQUIRED``: 29
bands across 8 rasters) for an H×W mosaic directly through
GeoTiffStreamWriter — realistic structure (patchy disturbances with known
years, compressible like real products) in O(row band) memory — then runs
:func:`write_change_maps` with ``mmu`` > 1 (forcing the full-raster sieve
plus the windowed zero-rewrite pass) and reports wall time and THIS
process's peak RSS, captured before any verification read.

Writes/merges CHANGESTREAM_r04.json.

Usage: python tools/change_stream_bench.py [--size=16000] [--mmu=9]
"""

from __future__ import annotations

import os
import shutil
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _measure import merge_json, rss_mb as _rss_mb  # noqa: E402

OUT_JSON = os.path.join(REPO, "CHANGESTREAM_r04.json")


def fabricate(seg_dir: str, h: int, w: int, band_rows: int) -> None:
    """Segment products with a known disturbance structure: ~30% of pixels
    carry one big drop (mag -0.4, 3 y) at a patch-dependent year, the rest
    only sub-threshold wiggle — so the change layer has real work to do
    and deflate sees realistic redundancy."""
    from land_trendr_tpu.io.geotiff import GeoMeta, GeoTiffStreamWriter

    geo = GeoMeta(pixel_scale=(30.0, 30.0, 0.0), tiepoint=(0, 0, 0, 5e5, 4e6, 0))
    NV, NM = 7, 6
    specs = {
        "vertex_years": (NV, np.float32),
        "vertex_fit_vals": (NV, np.float32),
        "seg_magnitude": (NM, np.float32),
        "seg_duration": (NM, np.float32),
        "seg_rate": (NM, np.float32),
        "model_valid": (1, np.uint8),
        "p_of_f": (1, np.float32),
        "rmse": (1, np.float32),
    }
    writers = {
        k: GeoTiffStreamWriter(
            os.path.join(seg_dir, f"{k}.tif"), h, w, depth, dt, geo=geo
        )
        for k, (depth, dt) in specs.items()
    }
    rng = np.random.default_rng(9)
    for y0 in range(0, h, band_rows):
        hb = min(band_rows, h - y0)
        # patch pattern: 64×64 blocks share a disturbance year (or none)
        by = (y0 + np.arange(hb)[:, None]) // 64
        bx = np.arange(w)[None, :] // 64
        patch = (by * 131 + bx * 17) % 10  # 0..9; <3 → disturbed patch
        disturbed = patch < 3
        d_year = 1990.0 + (patch * 3) % 20

        vy = np.empty((hb, w, NV), np.float32)
        vf = np.empty((hb, w, NV), np.float32)
        vy[..., 0] = 1984.0
        vy[..., 1] = np.where(disturbed, d_year, 1998.0)
        vy[..., 2] = np.where(disturbed, d_year + 3.0, 2012.0)
        vy[..., 3] = 2023.0
        vy[..., 4:] = 0.0
        vf[..., 0] = 0.6
        vf[..., 1] = np.where(disturbed, 0.62, 0.58)
        vf[..., 2] = np.where(disturbed, 0.22, 0.60)
        vf[..., 3] = np.where(disturbed, 0.45, 0.61)
        vf[..., 4:] = 0.0
        mag = np.zeros((hb, w, NM), np.float32)
        dur = np.zeros((hb, w, NM), np.float32)
        mag[..., :3] = vf[..., 1:4] - vf[..., :3]
        dur[..., :3] = vy[..., 1:4] - vy[..., :3]
        rate = np.where(dur > 0, mag / np.where(dur > 0, dur, 1.0), 0.0)
        arrays = {
            "vertex_years": vy,
            "vertex_fit_vals": vf,
            "seg_magnitude": mag,
            "seg_duration": dur,
            "seg_rate": rate.astype(np.float32),
            "model_valid": np.ones((hb, w, 1), np.uint8),
            "p_of_f": np.full((hb, w, 1), 0.01, np.float32),
            "rmse": rng.uniform(0.02, 0.06, (hb, w, 1)).astype(np.float32),
        }
        for k, a in arrays.items():
            writers[k].write(y0, 0, a)
    for wr in writers.values():
        wr.close()


def main() -> int:
    size, mmu = 16000, 9
    fab_only = False
    for a in sys.argv[1:]:
        if a.startswith("--size="):
            size = int(a.split("=", 1)[1])
        elif a.startswith("--mmu="):
            mmu = int(a.split("=", 1)[1])
        elif a == "--fabricate-only":
            fab_only = True
    h = w = size

    import jax

    jax.config.update("jax_platforms", "cpu")
    from land_trendr_tpu.ops.change import ChangeFilter, write_change_maps

    seg_dir = os.path.join(REPO, ".changestream_seg")
    dest = os.path.join(REPO, ".changestream_out")
    if fab_only:
        shutil.rmtree(seg_dir, ignore_errors=True)
        os.makedirs(seg_dir)
        fabricate(seg_dir, h, w, band_rows=512)
        return 0

    shutil.rmtree(dest, ignore_errors=True)
    # fabrication in a CHILD process: its band transients (~3 GB at 16k²)
    # must not pollute ru_maxrss — the measurement is the CHANGE layer's
    import subprocess

    t0 = time.perf_counter()
    subprocess.run(
        [sys.executable, os.path.abspath(__file__), f"--size={size}",
         "--fabricate-only"],
        check=True,
        cwd=REPO,
    )
    fab_s = time.perf_counter() - t0
    rss_fab = _rss_mb()

    t0 = time.perf_counter()
    paths = write_change_maps(
        seg_dir, dest, index="nbr", filt=ChangeFilter(min_mag=0.1), mmu=mmu
    )
    wall = time.perf_counter() - t0
    peak = _rss_mb()  # before any verification read

    from land_trendr_tpu.io.geotiff import read_geotiff_window

    yod = np.asarray(read_geotiff_window(paths["yod"], 0, 0, 128, w))
    mask = np.asarray(read_geotiff_window(paths["mask"], 0, 0, 128, w))
    assert ((yod > 0) == (mask > 0)).all()
    assert set(np.unique(yod[yod > 0])).issubset(
        {1991.0 + (p * 3) % 20 for p in range(3)}
    ), np.unique(yod[yod > 0])[:10]

    rec = {
        "height": h,
        "width": w,
        "pixels": h * w,
        "mmu": mmu,
        "fabricate_s": round(fab_s, 1),
        "change_wall_s": round(wall, 1),
        "peak_rss_mb": round(peak, 1),
        "rss_after_fabricate_mb": round(rss_fab, 1),
        "changed_frac_first_rows": round(float((mask > 0).mean()), 4),
        "out_bytes": {k: os.path.getsize(p) for k, p in paths.items()},
        "note": (
            "full segment-product set fabricated via stream writers, then "
            "write_change_maps with the mmu sieve + windowed zero-rewrite; "
            "peak_rss_mb covers fabrication + change mapping, captured "
            "before verification reads"
        ),
    }
    shutil.rmtree(seg_dir, ignore_errors=True)
    shutil.rmtree(dest, ignore_errors=True)
    merge_json(OUT_JSON, f"change_{h}x{w}_mmu{mmu}", rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
