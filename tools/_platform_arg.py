"""Shared ``--platform`` pre-parse for the measurement tools.

Must run BEFORE any jax.config use, so the tools call this at import time
rather than using argparse (which they reserve for positional args).
Accepts ``--platform=tpu`` and ``--platform tpu``; exact flag match only.
"""

from __future__ import annotations

import sys


def pop_platform_arg(default: str = "cpu") -> str:
    """Remove ``--platform[=| ]VALUE`` from ``sys.argv`` and return VALUE."""
    platform = default
    argv = sys.argv[1:]
    i = 0
    while i < len(argv):
        if argv[i] == "--platform" or argv[i].startswith("--platform="):
            if "=" in argv[i]:
                platform = argv[i].split("=", 1)[1]
                del argv[i]
            else:
                if i + 1 >= len(argv):
                    sys.exit("--platform requires a value (e.g. --platform=tpu)")
                platform = argv[i + 1]
                del argv[i : i + 2]
            continue
        i += 1
    sys.argv[1:] = argv
    return platform
