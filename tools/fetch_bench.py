"""Fetch-path benchmark: packed async device→host fetch vs per-product.

Builds a synthetic tile-output workload (the full segmentation product
set plus an FTV raster — ≥8 per-pixel products, the shape a real
multi-product run fetches every tile) and measures the fetch stage three
ways over the same tile sweep:

* ``per_product_sync`` — the pre-packing baseline: one synchronous
  ``np.asarray`` per product per tile (the driver's ``--no-packed-fetch``
  fallback, driven through the real :class:`runtime.fetch.TileFetcher`);
* ``packed_sync``   — ONE device-side pack + ONE transfer per tile,
  awaited immediately (isolates the transfer-count win);
* ``packed_async``  — the driver's production pipeline: the packed
  transfer of tile *i* lands while tile *i+1* packs, bounded at
  ``--depth`` in flight (adds the overlap win).

**Link model.** On this container's CPU backend a device→host "transfer"
is a zero-copy pointer hand-off, so the per-transfer cost that dominates
real accelerator links (SCENE_TPU_r04.json: fetch was 96% of scene wall
through the tunneled chip's ~per-request-latency-bound link) does not
exist locally.  The bench therefore models the link at the transfer
points — each transfer lands ``latency + bytes/bandwidth`` after it is
issued (``--link-ms`` / ``--link-gbps``, default a PCIe-class 1 ms /
8 GB/s; ``--link-ms 0 --link-gbps 0`` disables the model for raw
measurement on real hardware).  All host work — the pack program, the
materialization, the unpack/crop/sign restores — is genuinely executed,
and ``raw_local`` records the unmodeled walls alongside.  Parity (packed
≡ per-product, byte for byte, every product) is asserted on real arrays
every run.

Writes one JSON artifact (``--out``, e.g. ``FETCH_r08.json``).
``--smoke`` shrinks the workload to seconds scale — the tier-1 mode
``tests/test_fetch.py`` runs in CI.

Usage:
    python tools/fetch_bench.py --out FETCH_r08.json
    python tools/fetch_bench.py --smoke --out /tmp/fetch_smoke.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402

sys.path.insert(0, str(REPO / "tools"))
from _platform_arg import pop_platform_arg  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", pop_platform_arg())

import jax.numpy as jnp  # noqa: E402

from land_trendr_tpu.config import LTParams  # noqa: E402
from land_trendr_tpu.ops.segment import SegOutputs  # noqa: E402
from land_trendr_tpu.ops.tile import TileOutputs  # noqa: E402
from land_trendr_tpu.runtime import RunConfig  # noqa: E402
from land_trendr_tpu.runtime import fetch as fetchmod  # noqa: E402
from land_trendr_tpu.runtime.driver import TileSpec  # noqa: E402


def synth_outputs(px: int, ny: int, nv: int, nm: int, seed: int) -> TileOutputs:
    """A device-resident TileOutputs with realistic shapes/dtypes: random
    data is fine — the fetch stage moves bytes, it never looks at them."""
    rng = np.random.default_rng(seed)
    f32 = lambda *s: jnp.asarray(rng.random(s, np.float32))  # noqa: E731
    seg = SegOutputs(
        n_vertices=jnp.asarray(rng.integers(0, nv, px).astype(np.int32)),
        vertex_indices=jnp.asarray(
            rng.integers(-1, ny, (px, nv)).astype(np.int32)
        ),
        vertex_years=f32(px, nv),
        vertex_src_vals=f32(px, nv),
        vertex_fit_vals=f32(px, nv),
        seg_magnitude=f32(px, nm),
        seg_duration=f32(px, nm),
        seg_rate=f32(px, nm),
        rmse=f32(px),
        p_of_f=f32(px),
        model_valid=jnp.asarray(rng.integers(0, 2, px).astype(bool)),
        fitted=f32(px, ny),
        despiked=f32(px, ny),
    )
    out = TileOutputs(seg=seg, ftv={"ndvi": f32(px, ny)}, change=None)
    jax.block_until_ready(out)
    return out


class LinkModel:
    """Per-transfer cost model: a transfer issued now lands at
    ``now + latency_s + bytes/bw``; waiting sleeps out the remainder."""

    def __init__(self, latency_ms: float, gbps: float) -> None:
        self.latency_s = latency_ms / 1e3
        self.bps = gbps * 1e9

    @property
    def enabled(self) -> bool:
        return self.latency_s > 0 or self.bps > 0

    def land_at(self, nbytes: int) -> float:
        dt = self.latency_s + (nbytes / self.bps if self.bps else 0.0)
        return time.perf_counter() + dt

    def wait(self, land_at: float) -> None:
        while True:
            dt = land_at - time.perf_counter()
            if dt <= 0:
                return
            time.sleep(dt)


def run_per_product(cfg, outs, tiles, link: LinkModel) -> dict:
    """The production fallback path (TileFetcher packed=False) with the
    link model spliced into its one materialization seam."""
    fetcher = fetchmod.TileFetcher(cfg, packed=False)
    real_to_host = fetchmod._to_host

    def linked_to_host(arr):
        host = real_to_host(arr)
        link.wait(link.land_at(host.nbytes))  # synchronous: latency + wire
        return host

    fetchmod._to_host = linked_to_host if link.enabled else real_to_host
    try:
        t0 = time.perf_counter()
        for i, t in enumerate(tiles):
            fetcher.start(outs[i % len(outs)]).tile_arrays(t)
        wall = time.perf_counter() - t0
    finally:
        fetchmod._to_host = real_to_host
    s = fetcher.summary()
    return {"wall_s": wall, "stats": s}


def run_packed(cfg, outs, tiles, link: LinkModel, depth: int) -> dict:
    """The driver's packed pipeline shape: pack + async transfer, bounded
    in-flight queue, unpack on landed bytes.  ``depth=1`` = fully sync."""
    plan = fetchmod.build_plan(outs[0], cfg)
    wire = fetchmod.plan_wire_bytes(plan)
    queue: list[tuple[TileSpec, object, float]] = []

    def drain(limit: int) -> None:
        while len(queue) > limit:
            t, words, land_at = queue.pop(0)
            link.wait(land_at)
            host = np.asarray(words)
            fetchmod.unpack_tile(plan, host, t.h * t.w)

    t0 = time.perf_counter()
    for i, t in enumerate(tiles):
        words = fetchmod.pack_tile(outs[i % len(outs)], plan=plan)
        words.copy_to_host_async()
        queue.append((t, words, link.land_at(wire)))
        drain(depth - 1)
    drain(0)
    wall = time.perf_counter() - t0
    return {"wall_s": wall, "wire_bytes": wire}


def check_parity(cfg, outs, tiles) -> int:
    """Packed and per-product tile arrays must be byte-identical (real
    arrays, link model off)."""
    plan = fetchmod.build_plan(outs[0], cfg)
    checked = 0
    for i, t in enumerate(tiles[: min(3, len(tiles))]):
        out = outs[i % len(outs)]
        packed, mv_p = fetchmod.unpack_tile(
            plan, np.asarray(fetchmod.pack_tile(out, plan=plan)), t.h * t.w
        )
        ref, mv_u = (
            fetchmod.TileFetcher(cfg, packed=False).start(out).tile_arrays(t)
        )
        assert mv_p.sum() == mv_u, "model_valid rider mismatch"
        assert sorted(packed) == sorted(ref), (sorted(packed), sorted(ref))
        for k in ref:
            a, b = packed[k], ref[k]
            if a.dtype != b.dtype or a.shape != b.shape or a.tobytes() != b.tobytes():
                raise AssertionError(f"parity mismatch on {k} (tile {i})")
            checked += 1
    return checked


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tile", type=int, default=128,
                    help="tile edge in px (tile_px = tile^2)")
    ap.add_argument("--years", type=int, default=24)
    ap.add_argument("--tiles", type=int, default=16,
                    help="tiles per timed sweep (last one is an edge tile)")
    ap.add_argument("--depth", type=int, default=2,
                    help="async in-flight bound (RunConfig.fetch_depth)")
    ap.add_argument("--f16", action="store_true",
                    help="also fuse fetch_f16 casts into the pack")
    ap.add_argument("--link-ms", type=float, default=1.0,
                    help="modeled per-transfer latency (0 = no model)")
    ap.add_argument("--link-gbps", type=float, default=8.0,
                    help="modeled link bandwidth (0 = latency-only model)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per mode; MEDIAN wall reported")
    ap.add_argument("--seed", type=int, default=20260803)
    ap.add_argument("--out", default="FETCH_r08.json")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny workload, seconds not minutes (tier-1 CI)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.tile = min(args.tile, 64)
        args.years = min(args.years, 12)
        args.tiles = min(args.tiles, 4)
        args.reps = 1

    params = LTParams()
    nv, nm = params.max_vertices, params.max_segments
    px = args.tile * args.tile
    cfg = RunConfig(
        index="nbr", ftv_indices=("ndvi",), params=params,
        tile_size=args.tile, fetch_f16=args.f16, fetch_depth=args.depth,
    )
    # two distinct payloads alternated across the sweep (content never
    # matters to the fetch stage; two keep any caching honest), plus an
    # edge tile so the crop path is exercised
    outs = [
        synth_outputs(px, args.years, nv, nm, args.seed + k) for k in (0, 1)
    ]
    tiles = [
        TileSpec(i, 0, 0, args.tile, args.tile)
        for i in range(args.tiles - 1)
    ] + [TileSpec(args.tiles - 1, 0, 0, args.tile - 5, args.tile - 3)]
    link = LinkModel(args.link_ms, args.link_gbps)
    no_link = LinkModel(0.0, 0.0)

    # parity first (and the compile warmup for the pack program)
    parity_products = check_parity(cfg, outs, tiles)

    def median(mode_fn) -> dict:
        runs = [mode_fn() for _ in range(max(1, args.reps))]
        runs.sort(key=lambda r: r["wall_s"])
        return runs[len(runs) // 2]

    per_product = median(lambda: run_per_product(cfg, outs, tiles, link))
    packed_sync = median(lambda: run_packed(cfg, outs, tiles, link, 1))
    packed_async = median(
        lambda: run_packed(cfg, outs, tiles, link, args.depth)
    )
    # unmodeled walls: what this host really pays (on the CPU backend the
    # per-product path is zero-copy — exactly why fetch_packed="auto"
    # keeps it there)
    raw_pp = median(lambda: run_per_product(cfg, outs, tiles, no_link))
    raw_pk = median(lambda: run_packed(cfg, outs, tiles, no_link, args.depth))

    n = len(tiles)
    stats = per_product["stats"]
    result = {
        "workload": {
            "tile_px": px,
            "years": args.years,
            "nv": nv,
            "nm": nm,
            "tiles": n,
            "artifact_products": parity_products // min(3, n),
            "fetch_f16": args.f16,
            "bytes_per_tile_packed": packed_sync["wire_bytes"],
            "transfers_per_tile_per_product": stats["transfers"]
            // (stats["tiles"] or 1),
            "transfers_per_tile_packed": 1,
        },
        "platform": jax.default_backend(),
        "link_model": {
            "latency_ms": args.link_ms,
            "gbps": args.link_gbps,
            "note": (
                "transfers land latency + bytes/bandwidth after issue; "
                "models the per-transfer cost of a real accelerator link "
                "(absent on this CPU backend's zero-copy asarray) — all "
                "host work (pack/materialize/unpack) is real; raw_local "
                "records the unmodeled walls"
            ) if link.enabled else "disabled: raw hardware measurement",
        },
        "per_product_sync": {
            "wall_s": round(per_product["wall_s"], 4),
            "ms_per_tile": round(per_product["wall_s"] / n * 1e3, 3),
        },
        "packed_sync": {
            "wall_s": round(packed_sync["wall_s"], 4),
            "ms_per_tile": round(packed_sync["wall_s"] / n * 1e3, 3),
        },
        "packed_async": {
            "wall_s": round(packed_async["wall_s"], 4),
            "ms_per_tile": round(packed_async["wall_s"] / n * 1e3, 3),
            "depth": args.depth,
            "note": (
                "in this HOST-ONLY loop there is no device compute to "
                "overlap, so depth>1 cannot beat packed_sync locally (the "
                "queued packs contend for the same host cores); the "
                "driver issues fetches between block_until_ready calls, "
                "where the landing transfer overlaps the NEXT tile's "
                "device compute — that is where async pays"
            ),
        },
        "speedup_packed_sync": round(
            per_product["wall_s"] / packed_sync["wall_s"], 3
        ),
        "speedup_packed_async": round(
            per_product["wall_s"] / packed_async["wall_s"], 3
        ),
        "raw_local": {
            "per_product_ms_per_tile": round(raw_pp["wall_s"] / n * 1e3, 3),
            "packed_ms_per_tile": round(raw_pk["wall_s"] / n * 1e3, 3),
            "note": "no link model; CPU-backend asarray is zero-copy",
        },
        "parity": {
            "tiles_checked": min(3, n),
            "products_checked": parity_products,
            "ok": True,
        },
    }
    from tools._measure import write_json_atomic

    write_json_atomic(args.out, result)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
