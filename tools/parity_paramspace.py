"""Joint parameter-space parity fuzz: random LTParams × random series.

The suite's parity fuzz (tests/test_parity.py) randomizes series shape
heavily but varies parameters one axis at a time around the defaults.
This tool closes the gap for the north-star vertex-for-vertex contract:
every trial draws a RANDOM JOINT parameter combination (segments,
despike, overshoot, recovery constraints, selection thresholds, min-obs)
plus a fresh mixed-regime pixel batch, runs the float64 kernel against
the float64 oracle, and demands exact vertex agreement (indices, counts,
model_valid) on every pixel.

Writes PARITY_PARAMS_r03.json with the sampled space and any mismatch's
full repro (trial seed + params).  Usage:
    PYTHONPATH=. python tools/parity_paramspace.py [trials] [out.json]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # _population

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def sample_params(rng: np.random.Generator, ny: int):
    """A random valid LTParams whose candidate capacity fits the series."""
    from land_trendr_tpu.config import LTParams

    max_segments = int(rng.integers(1, 7))
    # keep candidate capacity comfortably under the year count
    max_overshoot = max(0, min(4, ny - (max_segments + 1) - 4))
    return LTParams(
        max_segments=max_segments,
        vertex_count_overshoot=int(rng.integers(0, max_overshoot + 1)),
        spike_threshold=float(rng.uniform(0.3, 1.0)),
        recovery_threshold=float(rng.choice([0.1, 0.25, 1.0, 10.0])),
        prevent_one_year_recovery=bool(rng.integers(0, 2)),
        p_val_threshold=float(rng.choice([0.01, 0.05, 0.15, 1.0])),
        best_model_proportion=float(rng.uniform(0.3, 1.0)),
        min_observations_needed=int(rng.integers(3, 11)),
    )


def make_batch(rng: np.random.Generator, px: int, ny: int):
    """Mixed-regime float64 series via the shared generator
    (tools/_population.py), with this tool's wider knobs: closer-to-edge
    disturbance years, smaller minimum magnitudes, elementwise spikes,
    and a per-trial random masking rate."""
    from _population import make_population as shared

    return shared(
        rng, px, ny,
        base_lo=0.4, base_hi=0.8, noise=0.01,
        d_margin_lo=2, d_margin_hi=2,
        mag_lo=0.05, rec_hi=0.2,
        spike="elementwise",
        mask_drop=float(rng.uniform(0.02, 0.35)),
    )


def main() -> int:
    trials = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    out_path = sys.argv[2] if len(sys.argv) > 2 else "PARITY_PARAMS_r03.json"
    seed_base = 1000
    for a in sys.argv[3:]:
        if a.startswith("--seed-base="):
            # fresh trial population (e.g. r4 ran base 2000 on top of r3's
            # 1000-based 256 trials — cumulative coverage, no replays)
            seed_base = int(a.split("=", 1)[1])
    px = 64

    from land_trendr_tpu.models import oracle
    from land_trendr_tpu.ops.segment import jax_segment_pixels

    t0 = time.time()
    total = 0
    exact = 0
    mismatches = []
    for trial in range(trials):
        rng = np.random.default_rng(seed_base + trial)
        ny = int(rng.choice([16, 24, 40]))
        params = sample_params(rng, ny)
        years, vals, mask = make_batch(rng, px, ny)

        out = jax_segment_pixels(years, vals, mask, params)
        vi = np.asarray(out.vertex_indices)
        nv = np.asarray(out.n_vertices)
        mv = np.asarray(out.model_valid)
        for i in range(px):
            ref = oracle.PixelSegmenter(params).segment(years, vals[i], mask[i])
            ok = (
                bool(ref.model_valid) == bool(mv[i])
                and int(ref.n_vertices) == int(nv[i])
                and np.array_equal(np.asarray(ref.vertex_indices), vi[i])
            )
            total += 1
            exact += ok
            if not ok and len(mismatches) < 10:
                mismatches.append(
                    {"trial": trial, "pixel": i, "ny": ny,
                     "params": params.to_dict()}
                )
        if (trial + 1) % 16 == 0:
            print(
                f"  {trial + 1}/{trials} trials, {exact}/{total} exact "
                f"({time.time() - t0:.0f}s)",
                file=sys.stderr, flush=True,
            )
        if (trial + 1) % 8 == 0:
            # every (params, ny) combo is a fresh kernel compilation; after
            # ~80 accumulated executables XLA:CPU's LLVM engine dies with
            # 'Cannot allocate memory' (JIT code region, not system RAM) —
            # drop the caches, the next trial recompiles its own kernel
            jax.clear_caches()

    rec = {
        "description": (
            "Joint parameter-space parity fuzz: random LTParams "
            "combinations x mixed-regime series, float64 kernel vs "
            "float64 oracle, exact vertex_indices/n_vertices/model_valid "
            "per pixel (north-star vertex-for-vertex contract)."
        ),
        "trials": trials,
        "seed_base": seed_base,
        "pixels_per_trial": px,
        "pixels_total": total,
        "exact": exact,
        "exact_rate": exact / total,
        "sampled_space": {
            "max_segments": "1..6",
            "vertex_count_overshoot": "0..4 (capped by ny)",
            "spike_threshold": "[0.3, 1.0]",
            "recovery_threshold": "{0.1, 0.25, 1.0, 10.0}",
            "prevent_one_year_recovery": "{False, True}",
            "p_val_threshold": "{0.01, 0.05, 0.15, 1.0}",
            "best_model_proportion": "[0.3, 1.0]",
            "min_observations_needed": "3..10",
            "n_years": "{16, 24, 40}",
        },
        "mismatches": mismatches,
        "elapsed_s": round(time.time() - t0, 1),
    }
    from tools._measure import write_json_atomic

    write_json_atomic(out_path, rec)
    print(json.dumps({k: rec[k] for k in ("pixels_total", "exact_rate", "elapsed_s")}))
    return 0 if exact == total else 1


if __name__ == "__main__":
    sys.exit(main())
