#!/bin/sh
# Pre-commit gate (README §Failure semantics / §Static analysis):
#
#   1. tools/lt_lint.py --changed  — the twelve LT AST invariant rules
#      over files modified vs HEAD (repo-level rules — LT004/LT005
#      coupling, LT006-LT008 interprocedural, LT009 replay purity and
#      LT011 seam coverage registries — run whenever one of their
#      sources changed).  A SARIF 2.1.0 log declaring all twelve rules
#      lands at $LT_LINT_SARIF (default .git/lt-lint.sarif, untracked)
#      so CI annotators can consume the findings without parsing our
#      JSON;
#   2. tools/check_events_schema.py over the COMMITTED event-stream
#      fixtures under tests/ (*.events.jsonl) — a fixture drifting from
#      the current schema (a renamed/removed field, a new required one)
#      fails here, pre-commit, instead of as a tier-1 surprise;
#   3. tools/check_events_schema.py — additionally over any event
#      streams passed as arguments (workdirs or events*.jsonl files).
#
# Install:  ln -s ../../tools/precommit.sh .git/hooks/pre-commit
# Exit codes follow the tools: 0 clean, 1 findings, 2 config error.

set -e
# git resolves the repo root regardless of how the hook is invoked —
# $0 is .git/hooks/pre-commit when installed as a symlink, so deriving
# the root from $0 would point inside .git/
repo="$(git rev-parse --show-toplevel 2>/dev/null)"
[ -n "$repo" ] || repo="$(cd "$(dirname "$0")/.." && pwd)"

# machine-readable findings artifact: inside the git dir by default so
# it is never committed; CI overrides LT_LINT_SARIF to its artifact dir.
# git rev-parse resolves the REAL git dir (a worktree's .git is a file,
# so a bare -d test would silently skip the artifact there)
sarif="${LT_LINT_SARIF:-}"
if [ -z "$sarif" ]; then
    gitdir="$(git -C "$repo" rev-parse --absolute-git-dir 2>/dev/null)"
    [ -n "$gitdir" ] && sarif="$gitdir/lt-lint.sarif"
fi
if [ -n "$sarif" ]; then
    python "$repo/tools/lt_lint.py" --changed --sarif "$sarif"
else
    python "$repo/tools/lt_lint.py" --changed
fi

# committed fixture streams lint against the CURRENT schema (newline-safe
# iteration is unnecessary: fixture names are repo-controlled)
fixtures="$(find "$repo/tests" -name '*.events.jsonl' 2>/dev/null)"
if [ -n "$fixtures" ]; then
    # shellcheck disable=SC2086
    python "$repo/tools/check_events_schema.py" $fixtures
fi

if [ "$#" -gt 0 ]; then
    python "$repo/tools/check_events_schema.py" "$@"
fi
