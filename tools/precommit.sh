#!/bin/sh
# Pre-commit gate (README §Failure semantics / §Static analysis):
#
#   1. tools/lt_lint.py --changed  — the five LT AST invariant rules over
#      files modified vs HEAD (repo-level coupling rules LT004/LT005 run
#      whenever one of their sources changed);
#   2. tools/check_events_schema.py — schema + value lint over any event
#      streams passed as arguments (workdirs or events*.jsonl files);
#      with no arguments this leg is skipped (there is no canonical
#      committed event stream — the lint's tier-1 home is the test
#      suite's generated streams).
#
# Install:  ln -s ../../tools/precommit.sh .git/hooks/pre-commit
# Exit codes follow the tools: 0 clean, 1 findings, 2 config error.

set -e
# git resolves the repo root regardless of how the hook is invoked —
# $0 is .git/hooks/pre-commit when installed as a symlink, so deriving
# the root from $0 would point inside .git/
repo="$(git rev-parse --show-toplevel 2>/dev/null)"
[ -n "$repo" ] || repo="$(cd "$(dirname "$0")/.." && pwd)"

python "$repo/tools/lt_lint.py" --changed

if [ "$#" -gt 0 ]; then
    python "$repo/tools/check_events_schema.py" "$@"
fi
