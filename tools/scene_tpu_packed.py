"""Packed-fetch TPU scene run (VERDICT r4 Weak #4 / next-round #5).

`SCENE_TPU_r04.json` measured the 25M-px scene's critical path at 96%
readback (write_s 710.7 of wall 737.9 s): every tile fetched the FULL
SegOutputs field set (~197 B/px f32) through the ~MB/s tunnel.  This run
repeats the same scene with the round-5 fetch economy:

* `RunConfig.products` — only the products the run writes are fetched
  (5 of 11 here; unselected fields never leave the device);
* `RunConfig.fetch_f16` — float products cross the wire as f16.

Together: ~33 B/px fetched vs ~197 (≈6×).  Agreement evidence: after the
packed run, N sample tiles are re-run with `fetch_f16=False` (same chip,
same kernel — decisions are identical by construction since packing only
changes the FETCH) and the artifact records the max f16-quantization
delta per float product plus bitwise equality of the decision products.

Usage:  python tools/scene_tpu_packed.py [--size 5000] [--out SCENE_TPU_r05.json]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PRODUCTS = ("n_vertices", "vertex_years", "seg_magnitude", "rmse", "model_valid")


def _bytes_per_px(ny: int, nv: int, nm: int) -> tuple[int, int]:
    """(full f32 set, packed subset incl. f16) manifest-fetch bytes/px."""
    full = 4 + nv * 4 * 4 + nm * 3 * 4 + 4 + 4 + 1  # all 11 products
    packed = 4 + nv * 2 + nm * 2 + 2 + 1            # subset, floats as f16
    return full, packed


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=5000)
    ap.add_argument("--tile-size", type=int, default=512)
    ap.add_argument("--sample-tiles", type=int, default=3)
    ap.add_argument("--out", type=str, default="SCENE_TPU_r05.json")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()

    import jax

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.ops.tile import process_tile_dn
    from land_trendr_tpu.runtime.driver import (
        RunConfig, _feed_tile, plan_tiles, run_stack,
    )
    from land_trendr_tpu.runtime.stack import stack_from_synthetic
    from land_trendr_tpu.utils.compilation_cache import enable_persistent_cache

    enable_persistent_cache()
    root = Path("/root/.scene_r05")
    if root.exists():
        shutil.rmtree(root)
    root.mkdir(parents=True)

    t0 = time.time()
    stack = stack_from_synthetic(
        make_stack(SceneSpec(width=args.size, height=args.size))
    )
    synth_s = time.time() - t0
    params = LTParams()
    cfg = RunConfig(
        index="nbr",
        params=params,
        tile_size=args.tile_size,
        workdir=str(root / "work"),
        out_dir=str(root / "out"),
        products=PRODUCTS,
        fetch_f16=True,
        impl="auto",
    )
    t0 = time.time()
    summary = run_stack(stack, cfg)
    wall = time.time() - t0

    # ---- agreement: re-run sample tiles with a full-precision fetch ----
    ny = stack.n_years
    nv, nm = params.max_vertices, params.max_segments
    tiles = plan_tiles(*stack.shape, args.tile_size)
    rng = np.random.default_rng(0)
    sample = rng.choice(len(tiles), size=min(args.sample_tiles, len(tiles)),
                        replace=False)
    from land_trendr_tpu.ops import indices as idx
    from land_trendr_tpu.runtime.manifest import TileManifest

    manifest = TileManifest(cfg.workdir, cfg.fingerprint(stack))
    agreement: dict[str, float] = {}
    decisions_equal = True
    bands = idx.required_bands(cfg.index, cfg.ftv_indices)
    for tid in sample:
        t = tiles[tid]
        dn, qa = _feed_tile(stack, t, cfg.tile_size * cfg.tile_size, bands)
        out = process_tile_dn(
            np.asarray(stack.years, np.float32), dn, qa, index=cfg.index,
            params=params, chunk=cfg.chunk_px, impl=cfg.impl,
        )
        px = t.h * t.w
        packed = manifest.load_tile(t.tile_id)
        sign = idx.DISTURBANCE_SIGN[cfg.index.lower()]
        ref = {
            "n_vertices": np.asarray(out.seg.n_vertices)[:px],
            "vertex_years": np.asarray(out.seg.vertex_years)[:px],
            "seg_magnitude": sign * np.asarray(out.seg.seg_magnitude)[:px],
            "rmse": np.asarray(out.seg.rmse)[:px],
            "model_valid": np.asarray(out.seg.model_valid)[:px],
        }
        for name in PRODUCTS:
            a, b = packed[name], ref[name]
            if a.dtype.kind in "iub":
                if not np.array_equal(a, b):
                    decisions_equal = False
            else:
                d = float(np.max(np.abs(a.astype(np.float64) - b)))
                agreement[name] = max(agreement.get(name, 0.0), d)

    full_bpp, packed_bpp = _bytes_per_px(ny, nv, nm)
    rec = {
        "description": "Config #3 scene on the real TPU with the round-5 "
                       "packed fetch (products subset + fetch_f16).",
        "platform": jax.default_backend(),
        "px": args.size * args.size,
        "tile_size": args.tile_size,
        "products": list(PRODUCTS),
        "fetch_f16": True,
        "summary": summary,
        "synth_s": round(synth_s, 1),
        "wall_s": round(wall, 1),
        "fetched_bytes_per_px": {"r04_full_f32": full_bpp, "packed": packed_bpp,
                                 "ratio": round(full_bpp / packed_bpp, 2)},
        "vs_SCENE_TPU_r04": {
            "write_s": 710.7416, "wall_s": 737.913,
            "note": "same scene generator/size/tile config; that run "
                    "fetched the full f32 product set",
        },
        "sample_tile_agreement_vs_full_precision_fetch": {
            "tiles": int(len(sample)),
            "decision_products_bitwise_equal": decisions_equal,
            "float_product_abs_delta_max": {
                k: round(v, 8) for k, v in agreement.items()
            },
            "note": "same kernel both legs — packing only changes the "
                    "fetch; float deltas are pure f16 quantization",
        },
    }
    line = json.dumps(rec, indent=1)
    print(line)
    Path(args.out).write_text(line + "\n")
    if not args.keep:
        shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
