"""GIGA: one composed gigapixel end-to-end run (VERDICT r4 Missing #3).

Round 4 rehearsed config #5 (BASELINE configs[4], CONUS class) piecewise:
assembly alone at 1.6e9 px, change streaming alone at 2.6e8 px, the full
driver at 2.5e7 px.  This tool runs the COMPOSED claim as one artifact:

    synthetic ≥1e9-px C2-named scene on disk (deflate, tiled)
      → lazy windowed ingest (stack.open_stack_dir_c2_lazy — no input
        cube ever materialises in RAM)
      → full driver segmentation into the fingerprinted tile manifest,
        HARD-KILLED part-way and resumed (the crash-resume path, not a
        polite checkpoint)
      → streamed raster assembly (BigTIFF auto)
      → on-device change products + the streamed spatial mmu sieve
    with every phase's wall time and peak RSS recorded → GIGA_r05.json.

Scale knobs keep the run honest but tractable on this 1-core host: the
pixel COUNT is real (default 32768² = 1.074e9 > 1e9); the year axis (12)
and a light parameter set (max_segments=2, no despike/overshoot — a
legitimate user configuration, fingerprinted like any other) bound the
per-pixel CPU cost; RunConfig.products bounds manifest+output bytes to
the products this run writes, exactly as a real gigapixel deployment
would.  Nothing is stubbed: every pixel flows disk → window read →
device kernel → manifest → assembled raster.

Usage:
    python tools/giga_run.py all [--size 32768] [--out-root /root/giga]
    python tools/giga_run.py gen|segment|assemble|sieve ...  (phases)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

NY = 12
YEAR0 = 1990
TILE = 1024
PRODUCTS = ("n_vertices", "vertex_years", "seg_magnitude", "rmse", "model_valid")


def _params():
    from land_trendr_tpu.config import LTParams

    return LTParams(max_segments=2, vertex_count_overshoot=0, spike_threshold=1.0)


def _cfg(root: Path, size: int):
    from land_trendr_tpu.ops.change import ChangeFilter
    from land_trendr_tpu.runtime.driver import RunConfig

    return RunConfig(
        index="nbr",
        params=_params(),
        tile_size=TILE,
        workdir=str(root / "work"),
        out_dir=str(root / "out"),
        products=PRODUCTS,
        change_filt=ChangeFilter(min_mag=0.1),
        manifest_compress="deflate",
        out_compress="deflate",
        impl="xla",
        chunk_px=262_144,
    )


def _scene_dir(root: Path) -> Path:
    return root / "scene"


def _c2_name(year: int, prod: str) -> str:
    return f"LT05_L2SP_045030_{year}0715_{year}0912_02_T1_{prod}.TIF"


_DN = lambda r: np.uint16(round((r + 0.2) / 2.75e-5))  # noqa: E731


def cmd_gen(args) -> dict:
    """Write the synthetic scene: nir (SR_B4) + swir2 (SR_B7) + QA_PIXEL
    per year, deflate tiled, block-streamed (bounded RSS)."""
    from land_trendr_tpu.io.geotiff import GeoMeta, GeoTiffStreamWriter

    size = args.size
    root = Path(args.out_root)
    scene = _scene_dir(root)
    scene.mkdir(parents=True, exist_ok=True)
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 553785.0, 5189625.0, 0.0),
        nodata=0.0,
    )
    t0 = time.time()
    band_rows = 2048
    # disturbance patch field: deterministic patch grid (128-px cells),
    # ~30% of cells disturbed, each with a per-cell year
    cell = 128
    ncell = size // cell
    rng = np.random.default_rng(20260731)
    cell_dist = rng.random((ncell, ncell)) < 0.3
    cell_year = rng.integers(2, NY - 2, (ncell, ncell))

    writers = {}
    for k in range(NY):
        year = YEAR0 + k
        for prod in ("SR_B4", "SR_B7", "QA_PIXEL"):
            writers[(k, prod)] = GeoTiffStreamWriter(
                str(scene / _c2_name(year, prod)), size, size, 1, np.uint16,
                geo=geo, compress="deflate", tile=512, compress_level=1,
            )
    for r0 in range(0, size, band_rows):
        h = min(band_rows, size - r0)
        crows = slice(r0 // cell, (r0 + h + cell - 1) // cell)
        # both fields get the SAME intra-cell row-offset slice — slicing
        # only dist would misalign the pair whenever band_rows % cell != 0
        dist = np.kron(cell_dist[crows], np.ones((cell, cell), bool))[
            r0 % cell :, :
        ][:h, :size]
        dyear = np.kron(cell_year[crows], np.ones((cell, cell), np.int64))[
            r0 % cell :, :
        ][:h, :size]
        brng = np.random.default_rng(r0)
        # noise quantized to 32-DN steps (0.00088 reflectance — well below
        # the disturbance signal, far above f32 rounding): the deflate
        # stream finds structure instead of raw mantissa entropy, which is
        # the difference between a ~5 h and a ~1 h scene write on 1 core
        q = 32 * 2.75e-5
        noise = np.round(brng.normal(0.0, 0.004, (h, size)) / q) * q
        for k in range(NY):
            disturbed = dist & (dyear <= k)
            nir = np.where(disturbed, 0.18, 0.45) + noise
            swir2 = np.where(disturbed, 0.25, 0.08) - noise
            qa = np.full((h, size), 1 << 6, np.uint16)
            if k % 5 == 2:  # a cloud band sweeping rows per year
                band = slice((r0 // 7) % max(1, h - 32), (r0 // 7) % max(1, h - 32) + 32)
                qa[band] |= 1 << 3
            writers[(k, "SR_B4")].write(
                r0, 0, ((nir + 0.2) / 2.75e-5).astype(np.uint16)[..., None]
            )
            writers[(k, "SR_B7")].write(
                r0, 0, ((swir2 + 0.2) / 2.75e-5).astype(np.uint16)[..., None]
            )
            writers[(k, "QA_PIXEL")].write(r0, 0, qa[..., None])
        print(f"gen rows {r0 + h}/{size} at {time.time()-t0:.0f}s", flush=True)
    for wtr in writers.values():
        wtr.close()
    bytes_total = sum(f.stat().st_size for f in scene.iterdir())
    return {
        "px": size * size, "ny": NY, "files": len(writers),
        "scene_bytes": bytes_total, "wall_s": round(time.time() - t0, 1),
    }


def cmd_segment(args) -> dict:
    from land_trendr_tpu.runtime.driver import run_stack
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    root = Path(args.out_root)
    stack = open_stack_dir_c2_lazy(str(_scene_dir(root)), bands=("nir", "swir2"))
    res = run_stack(stack, _cfg(root, args.size))
    return res


def cmd_assemble(args) -> dict:
    from land_trendr_tpu.runtime.driver import assemble_outputs
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    root = Path(args.out_root)
    t0 = time.time()
    stack = open_stack_dir_c2_lazy(str(_scene_dir(root)), bands=("nir", "swir2"))
    paths = assemble_outputs(stack, _cfg(root, args.size))
    out_bytes = sum(Path(p).stat().st_size for p in paths.values())
    return {
        "products": sorted(paths), "out_bytes": out_bytes,
        "wall_s": round(time.time() - t0, 1),
    }


def cmd_sieve(args) -> dict:
    from land_trendr_tpu.ops.change import sieve_change_rasters

    root = Path(args.out_root)
    t0 = time.time()
    sieve_change_rasters(str(root / "out"), mmu=11)
    return {"mmu": 11, "wall_s": round(time.time() - t0, 1)}


def _run_phase(phase: str, args, timeout=None, kill_after=None) -> dict:
    """Run one phase as a child process; the child self-reports peak RSS
    (resource.ru_maxrss) in its JSON line — no /usr/bin/time on this box."""
    cmd = [
        sys.executable, os.path.abspath(__file__),
        phase, "--size", str(args.size), "--out-root", args.out_root,
    ]
    t0 = time.time()
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    if kill_after is not None:
        try:
            proc.wait(timeout=kill_after)
        except subprocess.TimeoutExpired:
            proc.send_signal(signal.SIGKILL)  # crash, not a polite stop
        out, err = proc.communicate()
        return {"killed_after_s": kill_after, "rc": proc.returncode,
                "wall_s": round(time.time() - t0, 1)}
    out, err = proc.communicate(timeout=timeout)
    rec = {}
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                pass
    if proc.returncode != 0:
        raise RuntimeError(
            f"phase {phase} rc={proc.returncode}\n{err[-2000:]}"
        )
    rec["wall_s_total"] = round(time.time() - t0, 1)
    return rec


def cmd_all(args) -> dict:
    root = Path(args.out_root)
    root.mkdir(parents=True, exist_ok=True)
    result = {
        "px": args.size * args.size,
        "ny": NY,
        "tile": TILE,
        "products": list(PRODUCTS),
        "params": {"max_segments": 2, "vertex_count_overshoot": 0,
                   "spike_threshold": 1.0},
        "host": "1-core CPU (the build environment; the tunnel readback "
                "makes the chip slower than the CPU for manifest-heavy "
                "runs — SCENE_TPU_r04.json)",
    }
    result["gen"] = _run_phase("gen", args)
    # crash mid-segmentation, then resume: the manifest IS the checkpoint
    result["segment_killed"] = _run_phase(
        "segment", args, kill_after=args.kill_after
    )
    result["segment_resumed"] = _run_phase("segment", args)
    assert result["segment_resumed"].get("tiles_skipped_resume", 0) > 0, (
        "resume must skip tiles completed before the kill"
    )
    result["assemble"] = _run_phase("assemble", args)
    result["sieve"] = _run_phase("sieve", args)
    result["wall_s_total"] = round(sum(
        p.get("wall_s_total", p.get("wall_s", 0.0)) for p in (
            result["gen"], result["segment_killed"],
            result["segment_resumed"], result["assemble"], result["sieve"],
        )
    ), 1)
    result["peak_rss_mib_max"] = max(
        p["peak_rss_mib"] for p in (
            result["gen"], result["segment_resumed"], result["assemble"],
            result["sieve"],
        ) if p.get("peak_rss_mib")
    )
    out_path = REPO / "GIGA_r05.json"
    from tools._measure import write_json_atomic

    write_json_atomic(out_path, result, indent=1)
    print(json.dumps(result, indent=1))
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("phase", choices=["all", "gen", "segment", "assemble", "sieve"])
    ap.add_argument("--size", type=int, default=32768)
    ap.add_argument("--out-root", type=str, default="/root/giga")
    ap.add_argument("--kill-after", type=float, default=900.0)
    args = ap.parse_args()
    if args.phase == "all":
        cmd_all(args)
        return 0
    import jax

    if jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")
    rec = {
        "gen": cmd_gen, "segment": cmd_segment,
        "assemble": cmd_assemble, "sieve": cmd_sieve,
    }[args.phase](args)
    import resource

    rec["peak_rss_mib"] = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1
    )
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
