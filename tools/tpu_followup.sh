#!/bin/bash
# Second-stage TPU work, queued behind the bench watcher: the moment
# BENCH_r${LT_ROUND}.json holds a real accelerator number (bench_watch.sh
# succeeded inside an availability window), use the next green window for
# the f32-vs-f64 parity artifact the north star cares about
# (tools/parity_f32.py --f64-on-cpu: f32 pass on the chip, f64 reference
# on host CPU), then a TPU profile trace for the Pallas decision rule
# (tools/profile_stages.py — see ops/segment.py "Performance choice").
# Both inherit the persistent compile cache through their entry points.
cd /root/repo
R="${LT_ROUND:-04}"
LOG=/root/repo/BENCH_r${R}_attempts.log
BENCH=/root/repo/BENCH_r${R}.json
for i in $(seq 1 200); do
  # gate on a REAL bench success (device_platform != cpu), not mere file
  # existence — rounds 1-3 committed rc=124 diagnostic artifacts too
  if ! python -c "
import json, sys
r = json.load(open('$BENCH'))
sys.exit(0 if r.get('device_platform') not in (None, 'cpu') and r.get('value', 0) > 0 else 1)
" 2>/dev/null; then
    sleep 300
    continue
  fi
  if ! timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1; then
    sleep 300
    continue
  fi
  echo "[$(date -u +%FT%TZ)] followup: running TPU-f32 parity" >> "$LOG"
  if timeout 2400 python tools/parity_f32.py 65536 PARITY_f32_tpu.json \
       --f64-on-cpu >> "$LOG" 2>&1; then
    echo "[$(date -u +%FT%TZ)] followup: PARITY_f32_tpu.json written" >> "$LOG"
    git -C /root/repo add PARITY_f32_tpu.json >> "$LOG" 2>&1 && \
      git -C /root/repo commit -m "TPU-platform f32 parity artifact (watcher)" \
        -- PARITY_f32_tpu.json >> "$LOG" 2>&1
    # third-stage: a real TPU kernel profile (the artifact the Pallas
    # decision rule in ops/segment.py waits on); best-effort.  Re-probe
    # first (parity can take tens of minutes; the window may be gone) and
    # accept only a record whose OWN platform field is non-cpu — the
    # axon,cpu fallback must not be committed as a TPU profile.
    PROF=PROFILE_tpu_r${R}.json
    if timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1 \
       && timeout 2400 python tools/profile_stages.py 65536 "$PROF" \
            --platform=axon,cpu >>"$LOG" 2>&1 \
       && python -c "
import json, sys
sys.exit(0 if json.load(open('$PROF')).get('platform') != 'cpu' else 1)
" 2>/dev/null; then
      echo "[$(date -u +%FT%TZ)] followup: $PROF written" >> "$LOG"
      git -C /root/repo add "$PROF" >> "$LOG" 2>&1 && \
        git -C /root/repo commit -m "TPU stage profile artifact (watcher)" \
          -- "$PROF" >> "$LOG" 2>&1
    fi
    exit 0
  fi
  echo "[$(date -u +%FT%TZ)] followup: parity attempt failed; will retry" >> "$LOG"
  sleep 300
done
exit 1
