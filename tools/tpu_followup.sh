#!/bin/bash
# Second-stage TPU work, queued behind the bench watcher: the moment
# BENCH_r03.json exists (bench_watch.sh got a throughput number inside an
# availability window), use the next green window for the f32-vs-f64
# parity artifact the north star cares about (tools/parity_f32.py
# --f64-on-cpu: f32 pass on the chip, f64 reference on host CPU).
cd /root/repo
LOG=/root/repo/BENCH_r03_attempts.log
for i in $(seq 1 200); do
  if [ ! -f /root/repo/BENCH_r03.json ]; then
    sleep 300
    continue
  fi
  if ! timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1; then
    sleep 300
    continue
  fi
  echo "[$(date -u +%FT%TZ)] followup: running TPU-f32 parity" >> "$LOG"
  if timeout 2400 python tools/parity_f32.py 65536 PARITY_f32_tpu.json \
       --f64-on-cpu >> "$LOG" 2>&1; then
    echo "[$(date -u +%FT%TZ)] followup: PARITY_f32_tpu.json written" >> "$LOG"
    exit 0
  fi
  echo "[$(date -u +%FT%TZ)] followup: parity attempt failed; will retry" >> "$LOG"
  sleep 300
done
exit 1
