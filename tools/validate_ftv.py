"""Spot-validate FTV rasters of a finished run against the float64 oracle.

VERDICT r2 item #4 (config #4): a multi-index run writes NBR segmentation
plus NDVI/TCW fitted-trajectory rasters; this tool re-derives sampled
pixels' FTV series from first principles — input DNs → reflectance →
index series + QA/range mask → ``oracle.fit_to_vertices`` through the
run's own vertex rasters — and compares against what the run wrote.

The run computes FTV in float32 on device; the oracle is float64, so
agreement is expected at f32 precision (~1e-5 absolute on reflectance-
scale indices), not bitwise.

Usage:
  python tools/validate_ftv.py STACK_DIR OUT_DIR [--indices=ndvi,tcw]
         [--samples=64] [--out=FTV_VALIDATION.json] [--platform=cpu]
"""

from __future__ import annotations

import json
import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _platform_arg import pop_platform_arg  # noqa: E402

jax.config.update("jax_platforms", pop_platform_arg())

import numpy as np  # noqa: E402


def main() -> int:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    opts = dict(
        a[2:].split("=", 1) for a in sys.argv[1:] if a.startswith("--") and "=" in a
    )
    if len(args) != 2:
        sys.exit(__doc__)
    stack_dir, out_dir = args
    indices = tuple(opts.get("indices", "ndvi,tcw").split(","))
    n_samples = int(opts.get("samples", 64))
    out_path = opts.get("out", "FTV_VALIDATION.json")

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.geotiff import read_geotiff
    from land_trendr_tpu.models.oracle import fit_to_vertices
    from land_trendr_tpu.ops import indices as idx
    from land_trendr_tpu.runtime import load_stack_dir

    params = LTParams()
    stack = load_stack_dir(stack_dir)
    h, w = stack.shape
    ny = stack.n_years
    years = stack.years.astype(np.float64)

    vi_r, _, _ = read_geotiff(os.path.join(out_dir, "vertex_indices.tif"))
    nv_r, _, _ = read_geotiff(os.path.join(out_dir, "n_vertices.tif"))
    ftv_r = {}
    for name in indices:
        ftv_r[name], _, _ = read_geotiff(os.path.join(out_dir, f"ftv_{name}.tif"))
        assert ftv_r[name].shape == (ny, h, w), ftv_r[name].shape

    rng = np.random.default_rng(7)
    ys = rng.integers(0, h, size=n_samples)
    xs = rng.integers(0, w, size=n_samples)

    report: dict = {
        "stack_dir": stack_dir,
        "out_dir": out_dir,
        "n_samples": n_samples,
        "indices": {},
    }
    ok = True
    for name in indices:
        need = idx.required_bands(name)
        sign = idx.DISTURBANCE_SIGN[name]
        deltas = []
        for y, x in zip(ys, xs):
            sr = {
                b: np.asarray(
                    idx.scale_sr(stack.dn_bands[b][:, y, x].astype(np.float64))
                )
                for b in need
            }
            # the mask the run used ANDs QA with range validity over the
            # bands the RUN loaded (primary nbr + all ftv indices)
            run_bands = idx.required_bands("nbr", indices)
            sr_all = {
                b: np.asarray(
                    idx.scale_sr(stack.dn_bands[b][:, y, x].astype(np.float64))
                )
                for b in run_bands
            }
            mask = np.asarray(
                idx.qa_valid_mask(stack.qa[:, y, x])
            ) & np.asarray(idx.sr_valid_mask(sr_all))
            # compute_index already applies the disturbance-positive flip
            series = np.asarray(idx.compute_index(name, sr))
            vi = vi_r[:, y, x].astype(np.int64)
            nv = int(nv_r[y, x])
            ref = fit_to_vertices(years, series, mask, vi, nv, params)
            got = sign * ftv_r[name][:, y, x].astype(np.float64)
            deltas.append(np.abs(got - ref).max())
        deltas = np.asarray(deltas)
        rec = {
            "max_abs_delta": float(deltas.max()),
            "p99_abs_delta": float(np.percentile(deltas, 99)),
            "median_abs_delta": float(np.median(deltas)),
            "tolerance": 1e-3,
            "pass": bool((deltas <= 1e-3).all()),
        }
        ok &= rec["pass"]
        report["indices"][name] = rec
        print(f"ftv_{name}: max|Δ|={rec['max_abs_delta']:.2e} "
              f"p99={rec['p99_abs_delta']:.2e} pass={rec['pass']}",
              file=sys.stderr)

    report["pass"] = ok
    from tools._measure import write_json_atomic

    write_json_atomic(out_path, report)
    print(json.dumps(report))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
