"""Elastic pod-scheduling soak: churn (SIGKILL + late join) and slow-host legs.

The acceptance gate for the shared-manifest lease queue
(:mod:`land_trendr_tpu.runtime.leases`), in two legs:

* **churn** — independent worker processes share one workdir through the
  lease queue alone (no ``jax.distributed``).  The victim worker is
  SIGKILLed mid-run while holding leases; a second worker runs start to
  finish; a third JOINS LATE, after the run is already under way.  The
  run completes **without any resume**: survivors steal the victim's
  expired leases, every tile lands durably exactly once (one artifact
  per tile), and the artifacts are byte-identical to a clean single-host
  run.
* **slow-host** — a real two-process ``jax.distributed`` pod (the
  production driver flow) with an injected slow host (``slow`` fault
  kind on its compute waits, including one long park), run twice: static
  ``host_share`` split vs the elastic lease queue with speculation.
  ``lt_trace``'s analytics prove the collapse: pod busy-union idle gap
  and ``host_imbalance`` both drop, and the straggler-steered
  speculation path records at least one WIN (first durable done record
  belongs to the speculating host).

Full mode writes the ``ELASTIC_*.json`` artifact::

    python tools/elastic_soak.py --out ELASTIC_r13.json
    python tools/elastic_soak.py --smoke          # smaller, no artifact

``tools/perf_gate.py``'s scheduler leg drives :func:`slow_host_leg` at
smoke size with the same invariants.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _digest_workdir(workdir: str) -> dict:
    from tools.fault_soak import _digest_workdir as dig

    return dig(workdir)


def _manifest_records(workdir: str) -> list:
    import json as _json

    out = []
    with open(os.path.join(workdir, "manifest.jsonl")) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = _json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _lease_audit(workdir: str) -> dict:
    """Post-hoc audit of a run's lease log: done coverage, duplicate
    done records, steal/spec claims, and speculative WINS (the first
    done record's owner is the spec claimer)."""
    recs = _manifest_records(workdir)
    first_done: dict = {}
    done_counts: dict = {}
    steals: list = []
    specs: dict = {}
    for rec in recs:
        kind = rec.get("kind")
        if kind == "tile":
            tid = rec.get("tile_id")
            done_counts[tid] = done_counts.get(tid, 0) + 1
            if tid not in first_done:
                first_done[tid] = rec.get("owner")
        elif kind == "lease":
            if rec.get("mode") == "steal":
                steals.append((rec.get("tile_id"), rec.get("owner")))
            elif rec.get("mode") == "spec":
                # last spec claim per tile wins the bookkeeping; claims
                # are rare enough that this is exact in practice
                specs[rec.get("tile_id")] = rec.get("owner")
    spec_wins = sum(
        1 for tid, owner in specs.items() if first_done.get(tid) == owner
    )
    return {
        "tiles_done": len(done_counts),
        "duplicate_done_records": sum(
            v - 1 for v in done_counts.values() if v > 1
        ),
        "steals": len(steals),
        "speculations": len(specs),
        "spec_wins": spec_wins,
        "done_owners": sorted(
            {o for o in first_done.values() if o is not None}
        ),
    }


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn_worker(cfg_path: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, str(REPO / "tools" / "_elastic_worker.py"), cfg_path],
        env=_worker_env(),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
    )


def _write_worker_cfg(
    path: Path, workdir: str, size: int, tile: int, run_kw: dict,
    summary_path: "str | None" = None, height: "int | None" = None,
) -> str:
    """Write one ``tools/_elastic_worker.py`` config (shared with
    ``fault_soak``'s lease-kill case — one copy of the worker contract)."""
    cfg = {
        "workdir": workdir,
        "out_dir": workdir + "_o",
        "width": size,
        "height": size if height is None else height,
        "tile_size": tile,
        "seed": 11,
        "summary_path": summary_path,
        "run": run_kw,
    }
    path.write_text(json.dumps(cfg))
    return str(path)


def churn_leg(
    root: Path, size: int = 80, tile: int = 20, verbose: bool = True
) -> dict:
    """SIGKILL one host mid-lease, join one host late; no resume."""
    root.mkdir(parents=True, exist_ok=True)
    n_tiles = ((size + tile - 1) // tile) ** 2
    ttl = 1.0

    # clean single-host elastic reference (also proves 1-host lease mode)
    clean_wd = str(root / "churn_clean")
    p = _spawn_worker(_write_worker_cfg(
        root / "churn_clean.json", clean_wd, size, tile,
        {"lease_batch": 2, "lease_ttl_s": ttl},
    ))
    _, err = p.communicate(timeout=600)
    if p.returncode != 0:
        raise RuntimeError(f"clean elastic run failed:\n{err[-4000:]}")
    clean = _digest_workdir(clean_wd)

    wd = str(root / "churn_pod")
    # victim A: slow per tile so it is mid-run (holding leases) when
    # killed; batch 2 so it dies holding more than its in-flight tile
    a_cfg = _write_worker_cfg(
        root / "churn_a.json", wd, size, tile,
        {
            "lease_batch": 2,
            "lease_ttl_s": ttl,
            "fault_schedule": "seed=5,compute.wait%1.0=slow:0.3",
        },
    )
    b_cfg = _write_worker_cfg(
        root / "churn_b.json", wd, size, tile,
        {
            "lease_batch": 2,
            "lease_ttl_s": ttl,
            # modestly slow so real work remains when the late joiner's
            # cold jax startup completes — C must get to claim tiles
            "fault_schedule": "seed=6,compute.wait%1.0=slow:0.4",
        },
        summary_path=str(root / "churn_b_summary.json"),
    )
    c_cfg = _write_worker_cfg(
        root / "churn_c.json", wd, size, tile,
        {"lease_batch": 2, "lease_ttl_s": ttl},
        summary_path=str(root / "churn_c_summary.json"),
    )

    a = _spawn_worker(a_cfg)
    b = _spawn_worker(b_cfg)

    def _done_records() -> int:
        try:
            return sum(
                1 for r in _manifest_records(wd) if r.get("kind") == "tile"
            )
        except OSError:
            return 0

    def _a_holds_lease() -> bool:
        return any(
            r.get("kind") == "lease"
            and isinstance(r.get("owner"), str)
            and f":{a.pid}:" in r["owner"]
            for r in _manifest_records(wd)
        )

    # kill A once it demonstrably participates (holds leases) and the
    # run is clearly mid-flight — a kill at the starting line would not
    # prove steal-on-death
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if a.poll() is not None:
            raise RuntimeError(
                "victim worker exited before the kill "
                f"(rc={a.returncode}): {a.stderr.read()[-2000:]}"
            )
        if _done_records() >= 2 and _a_holds_lease():
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("victim never claimed a lease mid-run")
    os.kill(a.pid, signal.SIGKILL)
    a.communicate()
    t_kill = time.time()

    # late joiner C: enters once the run is demonstrably under way (its
    # cold jax startup adds several more seconds of genuine lateness)
    while _done_records() < 1:
        if b.poll() is not None:
            break
        time.sleep(0.05)
    c = _spawn_worker(c_cfg)

    _, err_b = b.communicate(timeout=600)
    _, err_c = c.communicate(timeout=600)
    if b.returncode != 0:
        raise RuntimeError(f"survivor worker failed:\n{err_b[-4000:]}")
    if c.returncode != 0:
        raise RuntimeError(f"late joiner failed:\n{err_c[-4000:]}")

    got = _digest_workdir(wd)
    if got != clean:
        raise AssertionError(
            "churn artifacts differ from the clean run (kill/steal/late-"
            "join changed bytes)"
        )
    audit = _lease_audit(wd)
    artifacts = len(list(Path(wd).glob("tile_*.npz")))
    if artifacts != n_tiles or audit["tiles_done"] != n_tiles:
        raise AssertionError(
            f"lost tiles: {artifacts} artifacts / {audit['tiles_done']} "
            f"done ids of {n_tiles}"
        )
    if audit["steals"] < 1:
        raise AssertionError(
            "no lease was stolen — the victim's death left nothing to "
            "steal (kill timing regression?)"
        )
    # the late joiner must have contributed durable work (ANY done
    # record of its own — under a tight TTL it may lose first-write
    # races on stolen tiles and still be a real participant)
    c_tiles = sum(
        1
        for r in _manifest_records(wd)
        if r.get("kind") == "tile"
        and isinstance(r.get("owner"), str)
        and f":{c.pid}:" in r["owner"]
    )
    if c_tiles < 1:
        raise AssertionError("late joiner completed no tiles")
    leg = {
        "tiles": n_tiles,
        "victim_killed_at": t_kill,
        "artifacts": artifacts,
        "artifacts_identical": True,
        "completed_without_resume": True,
        **{k: v for k, v in audit.items() if k != "done_owners"},
        "late_joiner_tiles": c_tiles,
    }
    if verbose:
        print(f"  ok: churn leg ({json.dumps(leg, default=str)})")
    return leg


#: the injected slow host's schedule: most compute waits +0.2s, with a
#: 2.5s park on invocations 3-4 — the flagged stragglers speculation
#: must rescue.  The park spec comes FIRST (FaultPlan picks the first
#: matching spec per invocation).
SLOW_SCHEDULE = (
    "seed=3,compute.wait@3*2=slow:2.5,compute.wait%0.9=slow:0.2"
)


def slow_host_leg(
    root: Path, size: int = 120, tile: int = 20, verbose: bool = True
) -> dict:
    """Static split vs elastic lease queue under one injected slow host
    (two-process ``jax.distributed`` pod), proven via ``lt_trace``."""
    from tests._pod_launch import launch_pod

    from land_trendr_tpu.obs.events import discover_event_files
    from land_trendr_tpu.obs.spans import assemble_pod_trace

    root.mkdir(parents=True, exist_ok=True)
    worker = str(REPO / "tests" / "_driver_worker.py")
    n_tiles = ((size + tile - 1) // tile) ** 2
    results: dict = {}
    for mode in ("static", "elastic"):
        wd = str(root / f"slow_{mode}")
        common = {
            "retry_backoff_s": 0.0,
            "straggler_k": 2.0,
            "straggler_min_tiles": 3,
        }
        if mode == "elastic":
            common.update(
                lease_batch=1,
                lease_ttl_s=20.0,
                speculate=True,
                # the sampler thread is the in-flight straggler scanner —
                # the verdict must fire WHILE the slow host is parked
                flight=True,
                sampler_interval_s=0.1,
            )
        ov_paths = []
        for i in range(2):
            ov = dict(common)
            if i == 1:
                ov["fault_schedule"] = SLOW_SCHEDULE
            p = root / f"slow_{mode}_ov{i}.json"
            p.write_text(json.dumps(ov))
            ov_paths.append(str(p))
        summaries = [str(root / f"slow_{mode}_s{i}.json") for i in range(2)]
        import shutil

        launch_pod(
            worker,
            lambda i: [
                "2", str(i), wd, summaries[i], str(size), str(tile), "1",
                ov_paths[i],
            ],
            before_attempt=lambda: shutil.rmtree(wd, ignore_errors=True),
            timeout=900.0,
        )
        trace = assemble_pod_trace(discover_event_files(wd, process_count=2))
        pod_wall = trace["pod"]["wall_s"] or 0.0
        idle_gap = sum(
            max(pod_wall - (h.get("busy_s") or 0.0), 0.0)
            for h in trace["hosts"]
        )
        audit = _lease_audit(wd)
        per = [json.load(open(s)) for s in summaries]
        results[mode] = {
            "pod_wall_s": round(pod_wall, 3),
            "host_walls_s": [h.get("wall_s") for h in trace["hosts"]],
            "busy_s": [h.get("busy_s") for h in trace["hosts"]],
            "idle_gap_pod_s": round(idle_gap, 3),
            "host_imbalance": trace["pod"].get("host_imbalance"),
            "stragglers": trace["pod"].get("stragglers"),
            "tiles_stolen": trace["pod"].get("tiles_stolen"),
            "tiles_speculated": trace["pod"].get("tiles_speculated"),
            "tiles_done_per_host": [h.get("tiles_done") for h in trace["hosts"]],
            "spec_wins": audit["spec_wins"],
            "duplicate_done_records": audit["duplicate_done_records"],
            "unique_done_tiles": audit["tiles_done"],
            "pixels_per_host": [s.get("pixels") for s in per],
        }
        # exact no-lost-tile invariant, both modes
        if audit["tiles_done"] != n_tiles:
            raise AssertionError(
                f"{mode}: {audit['tiles_done']} unique done tiles of "
                f"{n_tiles}"
            )
        artifacts = len(list(Path(wd).glob("tile_*.npz")))
        if artifacts != n_tiles:
            raise AssertionError(
                f"{mode}: {artifacts} artifacts of {n_tiles} (lost or "
                "double-written tiles)"
            )
        if verbose:
            print(f"  ok: slow-host {mode} ({json.dumps(results[mode])})")

    st, el = results["static"], results["elastic"]
    if not (el["idle_gap_pod_s"] < st["idle_gap_pod_s"]):
        raise AssertionError(
            f"elastic idle gap {el['idle_gap_pod_s']}s did not collapse "
            f"vs static {st['idle_gap_pod_s']}s"
        )
    if not (
        st["host_imbalance"] and el["host_imbalance"]
        and el["host_imbalance"] < st["host_imbalance"]
    ):
        raise AssertionError(
            f"elastic host_imbalance {el['host_imbalance']} did not drop "
            f"vs static {st['host_imbalance']}"
        )
    if el["spec_wins"] < 1:
        raise AssertionError(
            "no speculative win: the straggler-steered path never beat "
            "the parked owner"
        )
    results["deltas"] = {
        "idle_gap_collapse": round(
            st["idle_gap_pod_s"] / el["idle_gap_pod_s"], 3
        ) if el["idle_gap_pod_s"] else None,
        "imbalance_drop": round(
            st["host_imbalance"] - el["host_imbalance"], 3
        ),
        "pod_wall_speedup": round(
            st["pod_wall_s"] / el["pod_wall_s"], 3
        ) if el["pod_wall_s"] else None,
    }
    if verbose:
        print(f"  ok: slow-host deltas {json.dumps(results['deltas'])}")
    return results


def soak(
    smoke: bool = False, keep: "str | None" = None, verbose: bool = True
) -> dict:
    root = Path(keep or tempfile.mkdtemp(prefix="lt_elastic_soak_"))
    root.mkdir(parents=True, exist_ok=True)
    # sizes are identical in both modes — each leg's scene is already
    # the smallest that exercises it reliably (the late joiner needs the
    # run to outlive its cold jax startup; the slow host must run enough
    # tiles to reach SLOW_SCHEDULE's park with its straggler median
    # seeded) — smoke only skips the artifact file
    size_churn, size_slow = 80, 120
    report = {
        "smoke": smoke,
        "churn": churn_leg(root, size=size_churn, verbose=verbose),
        "slow_host": slow_host_leg(root, size=size_slow, verbose=verbose),
        "ok": True,
    }
    if keep is None:
        import shutil

        shutil.rmtree(root, ignore_errors=True)
    return report


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="smaller scenes, no artifact file")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep workdirs under DIR for post-mortem")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here (the ELASTIC_* artifact)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    report = soak(smoke=args.smoke, keep=args.keep)
    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(json.dumps({"ok": report["ok"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
