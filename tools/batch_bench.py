"""Cross-job continuous-batching bench: a flood of small jobs, packed.

Replays the fleet's dominant workload — MANY identical small-AOI
segmentation jobs — through the PR-16 loadgen rig (closed loop, every
virtual client submits at once) against two real
:class:`~land_trendr_tpu.serve.server.SegmentationServer` instances
over the same synthetic stack:

* the **base** leg runs with ``batch=False``: one job = one run = one
  pipeline, so every tiny job pays its own dispatch, padding and
  pipeline-drain overhead (today's path);
* the **batched** leg runs with ``batch=True``: the dispatcher
  coalesces the queued same-affinity jobs behind ONE shared launch and
  demuxes each durable tile into every member's own manifest, so the
  members' queue turns are near-zero-work resumes.

A discarded **warmup** job runs first so BOTH legs measure warm
steady state — the program cache compiled, the stack touched.  Fleet
floods are steady-state traffic; cold-start amortization is
``tools/serve_bench.py``'s story, and folding it into either leg here
would credit batching with a compile it didn't remove.  (This also
makes the report deterministic across contexts: standalone and inside
the perf gate's long-lived process read the same numbers.)

The speedup is never bought with correctness: every job workdir in
BOTH legs is digest-compared against one reference (all jobs are
identical, so all artifacts must be byte-identical, batched or not).
Device-side packing quality is read back from the batched server's
``batch_launch``/``batch_demux`` events (jobs per launch, padded-pixel
occupancy, demuxed tiles).

The report also carries a capacity-planner comparison: a closed-loop
flood measures each leg's saturation throughput, which is (to first
order) where the open-loop p99 knee sits on the
``tools/capacity_bench.py`` replicas-vs-QPS curve — so
``knee_shift_x`` says how far right batching moves the knee for this
one-replica, small-AOI workload.

    python tools/batch_bench.py --smoke --out /tmp/batch_smoke.json
    python tools/batch_bench.py --out BATCH_r18.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402


def _digest_workdir(workdir: str) -> dict:
    """tile_id → {array name → sha256} (array-content identity, like
    fault_soak: npz zip metadata legitimately differs run to run)."""
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


class _ServerClient:
    """Loadgen client driving one :class:`SegmentationServer` in
    process (the :class:`InProcClient` shape, pointed at a server
    instead of a router): submissions go through the server's real
    admission control, and status polls keep working after the bounded
    server closes its HTTP socket — losing the race to one final GET
    is not a bench failure.  Records every accepted job id so the
    bench can digest each job's workdir afterwards."""

    def __init__(self, server) -> None:
        self._server = server
        self.job_ids: "list[str]" = []
        self._lock = threading.Lock()

    def submit(self, payload: dict) -> "tuple[str | None, str | None]":
        from land_trendr_tpu.serve.server import Rejection

        try:
            snap = self._server.submit(payload, source="loadgen")
        except Rejection as e:
            return None, e.reason
        with self._lock:
            self.job_ids.append(snap["job_id"])
        return snap["job_id"], None

    def status(self, job_id: str) -> "str | None":
        snap = self._server.job_status(job_id)
        return None if snap is None else snap.get("state")


def _batch_events(workdir: str) -> "tuple[list, list]":
    """(batch_launch records, batch_demux records) from the server's
    events stream — the packing-quality ground truth."""
    launches: list = []
    demuxes: list = []
    path = Path(workdir) / "events.jsonl"
    if not path.exists():
        return launches, demuxes
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line: not this bench's concern
            if rec.get("ev") == "batch_launch":
                launches.append(rec)
            elif rec.get("ev") == "batch_demux":
                demuxes.append(rec)
    return launches, demuxes


def run_leg(
    name: str,
    root: str,
    stack_dir: str,
    *,
    tile: int,
    n_jobs: int,
    batch: bool,
    window_ms: float,
) -> dict:
    """One flood: ``n_jobs`` identical small jobs, closed loop with
    ``n_jobs`` virtual clients (everything queues at once — the
    batched dispatcher sees the whole flood), drained to terminal."""
    from land_trendr_tpu.fleet.capacity import percentile
    from land_trendr_tpu.loadgen import LoadConfig, LoadRunner
    from land_trendr_tpu.serve import SegmentationServer, ServeConfig

    cfg = ServeConfig(
        workdir=str(Path(root) / name),
        serve_port=0,
        max_jobs=n_jobs,
        # the whole flood must queue at once for the dispatcher to see
        # it — admission caps are the router/capacity benches' story
        tenant_max_inflight=n_jobs,
        feed_cache_mb=64,
        batch=batch,
        batch_window_ms=window_ms,
    )
    server = SegmentationServer(cfg)
    client = _ServerClient(server)

    def payload_fn(req) -> dict:
        # one small-AOI preset for the whole flood: identical payloads
        # → identical affinity keys → the batched leg may coalesce
        # every queued job (req.shape is ignored on purpose)
        return {
            "stack_dir": stack_dir,
            "tile_size": tile,
            "params": {"max_segments": 4, "vertex_count_overshoot": 2},
            "tenant": req.tenant,
            "trace_id": req.trace_id,
        }

    runner = LoadRunner(
        LoadConfig(
            mode="closed",
            duration_s=900.0,
            requests=n_jobs,
            workers=n_jobs,
            seed=18,
            tenants=2,
            timeout_s=600.0,
        ),
        client,
        payload_fn,
    )
    box: dict = {}
    errors: list = []

    def drive() -> None:
        try:
            box["report"] = runner.run(phase=name)
        except Exception as e:  # surfaces in the report, fails the bench
            errors.append(f"{type(e).__name__}: {e}")
            server.stop()

    t = threading.Thread(target=drive, name=f"batch-bench-{name}")
    t.start()
    # let the flood actually queue before the first pop: the workload
    # under test is a standing backlog, not a trickle — and BOTH legs
    # pay the same beat, so the comparison is untouched
    time.sleep(0.1)
    server.serve_forever()  # drains the flood, then shuts down
    t.join(timeout=60)
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")
    rep = box["report"]

    workdirs = []
    for job_id in client.job_ids:
        snap = server.job_status(job_id)
        if snap is None or snap["state"] != "done":
            state = None if snap is None else snap.get("state")
            raise RuntimeError(
                f"{name}: job {job_id} ended {state}: "
                f"{None if snap is None else snap.get('error')}"
            )
        workdirs.append(snap["workdir"])

    launches, demuxes = _batch_events(cfg.workdir)
    lat = sorted(o.latency_s for o in rep.outcomes if o.latency_s)
    leg = {
        "batch": batch,
        "jobs": n_jobs,
        "done": rep.done,
        "failed": rep.failed,
        "rejected": rep.rejected,
        "wall_s": round(rep.wall_s, 4),
        "throughput_jobs_s": round(rep.done / rep.wall_s, 4)
        if rep.wall_s
        else None,
        "p50_s": round(percentile(lat, 50.0), 4) if lat else None,
        "p99_s": round(percentile(lat, 99.0), 4) if lat else None,
        "launches": len(launches),
        "jobs_coalesced": sum(r["jobs"] for r in launches),
        "jobs_per_launch": round(
            sum(r["jobs"] for r in launches) / len(launches), 2
        )
        if launches
        else None,
        "occupancy": round(
            sum(r["occupancy"] for r in launches) / len(launches), 4
        )
        if launches
        else None,
        "demuxed_tiles": sum(r["tiles"] for r in demuxes),
    }
    return {"leg": leg, "workdirs": workdirs}


def run_bench(
    size: int, years: int, tile: int, n_jobs: int, window_ms: float, root: str
) -> dict:
    from land_trendr_tpu.io.synthetic import (
        SceneSpec,
        make_stack,
        write_stack_c2,
    )

    stack_dir = str(Path(root) / "stack")
    write_stack_c2(
        stack_dir,
        make_stack(
            SceneSpec(
                width=size,
                height=size,
                year_start=2000,
                year_end=2000 + years - 1,
                seed=18,
            )
        ),
    )

    # one discarded solo job: compile + first-touch land here, so both
    # measured legs read warm steady state (see the module docstring)
    warmup = run_leg(
        "warmup", root, stack_dir,
        tile=tile, n_jobs=1, batch=False, window_ms=window_ms,
    )

    base = run_leg(
        "base", root, stack_dir,
        tile=tile, n_jobs=n_jobs, batch=False, window_ms=window_ms,
    )
    batched = run_leg(
        "batched", root, stack_dir,
        tile=tile, n_jobs=n_jobs, batch=True, window_ms=window_ms,
    )

    # parity: every job in BOTH legs must match one non-empty
    # reference — all payloads are identical, so batching may change
    # packing, never bytes
    reference = _digest_workdir(base["workdirs"][0])
    parity_ok = bool(reference) and all(
        _digest_workdir(wd) == reference
        for leg in (base, batched)
        for wd in leg["workdirs"]
    )

    b, p = base["leg"], batched["leg"]
    speedup = (
        round(p["throughput_jobs_s"] / b["throughput_jobs_s"], 2)
        if b["throughput_jobs_s"] and p["throughput_jobs_s"]
        else None
    )
    report = {
        "schema": "lt-batch-bench-v1",
        "workload": {
            "scene_px": size * size,
            "years": years,
            "tile_size": tile,
            "tiles_per_job": ((size + tile - 1) // tile) ** 2,
            "jobs": n_jobs,
            "batch_window_ms": window_ms,
            "mode": "closed",
            "warmup_s": warmup["leg"]["wall_s"],
        },
        "base": b,
        "batched": p,
        # the headline: packing the flood behind shared launches
        "speedup_batched": speedup,
        # a closed-loop flood measures saturation throughput — to
        # first order, where the open-loop p99 knee sits on the
        # capacity planner's one-replica curve (CAPACITY_r17.json)
        "capacity": {
            "base_knee_qps_est": b["throughput_jobs_s"],
            "batched_knee_qps_est": p["throughput_jobs_s"],
            "knee_shift_x": speedup,
        },
        "invariants": {
            "all_done": b["done"] == n_jobs and p["done"] == n_jobs
            and b["failed"] == p["failed"] == 0
            and b["rejected"] == p["rejected"] == 0,
            "base_never_batches": b["launches"] == 0,
            "batched_coalesces": p["launches"] >= 1
            and (p["jobs_per_launch"] or 0) > 1,
            "batched_faster": (speedup or 0) > 1.0,
            "p99_lower": b["p99_s"] is not None
            and p["p99_s"] is not None
            and p["p99_s"] < b["p99_s"],
        },
        "parity_ok": parity_ok,
    }
    return report


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale tier-1 mode (tiny flood)")
    ap.add_argument("--size", type=int, default=None,
                    help="scene edge px (default: 96 smoke / 128 full)")
    ap.add_argument("--years", type=int, default=None,
                    help="stack years (default: 12 smoke / 16 full)")
    ap.add_argument("--tile", type=int, default=None,
                    help="tile size (default: 32 smoke / 32 full)")
    ap.add_argument("--jobs", type=int, default=None,
                    help="flood size (default: 8 smoke / 12 full)")
    ap.add_argument("--window-ms", type=float, default=None,
                    help="batch window (default: 150 smoke / 300 full)")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the bench workdirs under DIR")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    # warm per-job device work must dominate the fixed resume cost a
    # member's queue turn still pays, or the flood measures dispatcher
    # overhead instead of packing — hence scenes this size, not 64px
    size = args.size or (96 if args.smoke else 128)
    years = args.years or (12 if args.smoke else 16)
    tile = args.tile or 32
    n_jobs = args.jobs or (8 if args.smoke else 12)
    window_ms = args.window_ms or (150.0 if args.smoke else 300.0)

    root = args.keep or tempfile.mkdtemp(prefix="lt_batch_bench_")
    Path(root).mkdir(parents=True, exist_ok=True)
    try:
        report = run_bench(size, years, tile, n_jobs, window_ms, root)
    finally:
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    report["smoke"] = bool(args.smoke)
    ok = report["parity_ok"] and all(report["invariants"].values())
    report["ok"] = ok
    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(
        json.dumps(
            {
                "ok": ok,
                "base_jobs_s": report["base"]["throughput_jobs_s"],
                "batched_jobs_s": report["batched"]["throughput_jobs_s"],
                "speedup_batched": report["speedup_batched"],
                "p99_s": [report["base"]["p99_s"], report["batched"]["p99_s"]],
                "jobs_per_launch": report["batched"]["jobs_per_launch"],
                "occupancy": report["batched"]["occupancy"],
                "invariants": report["invariants"],
                "parity_ok": report["parity_ok"],
            }
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
