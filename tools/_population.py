"""Shared mixed-regime synthetic pixel population for the parity tools.

One generator, five regimes — exp-recovery disturbance, step, linear
trend, scaled random walk, flat — plus spikes, noise, and masking, in the
disturbance-positive convention the kernel takes.  ``tools/parity_f32.py``
uses the defaults (its historical literal values and RNG draw order);
``tools/parity_paramspace.py`` passes its wider knob settings.  Keeping
this in one place means the two parity artifacts always sample the same
population FAMILY and a shape fix reaches both.
"""

from __future__ import annotations

import numpy as np


def make_population(
    rng: np.random.Generator,
    px: int,
    ny: int,
    *,
    base_lo: float = 0.45,
    base_hi: float = 0.75,
    noise: float = 0.012,
    d_margin_lo: int = 4,
    d_margin_hi: int = 4,
    mag_lo: float = 0.1,
    mag_hi: float = 0.5,
    rec_lo: float = 0.02,
    rec_hi: float = 0.15,
    spike: str = "rows",       # "rows": one spike col on a fraction of
    spike_frac: float = 0.2,   # pixels; "elementwise": per-cell probability
    spike_prob: float = 0.03,
    mask_drop: float = 0.08,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(years, disturbance-positive float64 series, validity mask)."""
    years = np.arange(1984, 1984 + ny, dtype=np.int32)
    t = np.arange(ny, dtype=np.float64)[None, :]
    kind = rng.integers(0, 5, size=(px, 1))

    base = rng.uniform(base_lo, base_hi, size=(px, 1))
    noise_arr = rng.normal(0.0, noise, size=(px, ny))

    d_year = rng.integers(d_margin_lo, ny - d_margin_hi, size=(px, 1))
    mag = rng.uniform(mag_lo, mag_hi, size=(px, 1))
    rec = rng.uniform(rec_lo, rec_hi, size=(px, 1))
    dt = np.maximum(t - d_year, 0.0)
    disturbance = np.where(t >= d_year, mag * np.exp(-rec * dt), 0.0)

    step = np.where(t >= d_year, mag, 0.0)
    trend = rng.uniform(-0.01, 0.01, size=(px, 1)) * t
    walk = np.cumsum(rng.normal(0, 0.03, size=(px, ny)), axis=1)

    traj = base - np.where(
        kind == 0, disturbance,
        np.where(kind == 1, step,
                 np.where(kind == 2, trend,
                          np.where(kind == 3, walk * 0.2, 0.0))),
    )
    if spike == "rows":
        spike_rows = rng.uniform(size=(px, 1)) < spike_frac
        spike_col = rng.integers(0, ny, size=(px,))
        spike_amp = rng.uniform(0.2, 0.8, size=(px,))
        traj[np.arange(px), spike_col] += np.where(
            spike_rows[:, 0], spike_amp, 0.0
        )
    elif spike == "elementwise":
        cells = rng.uniform(size=(px, ny)) < spike_prob
        traj = traj + np.where(cells, rng.uniform(0.2, 0.8, (px, ny)), 0.0)
    else:
        raise ValueError(f"spike={spike!r} not 'rows'|'elementwise'")
    traj = traj + noise_arr
    mask = rng.uniform(size=(px, ny)) > mask_drop
    return years, -traj, mask
