"""Capacity bench: scaling curves + the byte-identical replay proof.

One run sweeps replica counts x offered QPS with the seeded load rig
(``land_trendr_tpu.loadgen``) against live ``lt route`` fleets, then:

* assembles every sweep cell's latency truth through the PR-15
  request-trace store (``obs.reqtrace`` — fleet event streams, not
  client clocks), folding p50/p99/goodput per cell;
* finds the knee of each replica count's offered-QPS-vs-p99 curve and
  names the dominant blame component there
  (``land_trendr_tpu.fleet.capacity``);
* replays every leg's recorded decision log (``--decision-log``)
  through fresh pure machines and byte-compares the outputs — plus a
  scripted autoscaler/dispatcher history for the clock-free speedup
  number — the "the simulator IS the dispatcher" proof.

The report lands as ``CAPACITY_r17.json``; ``tools/perf_gate.py``'s
capacity leg re-checks the replay and schema on every gate run.

Usage::

    python tools/capacity_bench.py --smoke --out /tmp/cap.json
    python tools/capacity_bench.py --out CAPACITY_r17.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.fleet.capacity import (
    REPORT_SCHEMA,
    ReplayReport,
    assemble_sweep,
    dominant_blame,
    mark_knee,
    percentile,
    replay_decisions,
    validate_report,
    write_scripted_history,
)
from land_trendr_tpu.fleet.scheduling import DECISIONS_NAME
from land_trendr_tpu.loadgen import InProcClient, LoadConfig, LoadRunner
from land_trendr_tpu.loadgen.trace import SHAPE_PARAMS


def _write_scene(root: Path, size: int, years: int) -> str:
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack

    d = str(root / "stack")
    write_stack(
        d,
        make_stack(SceneSpec(
            width=size, height=size, year_start=2000,
            year_end=2000 + years - 1, seed=13,
        )),
    )
    return d


def _payload_fn(stack_dir: str, tile: int):
    def fn(req) -> dict:
        return {
            "stack_dir": stack_dir,
            "tile_size": tile,
            "tenant": req.tenant,
            "params": dict(SHAPE_PARAMS[req.shape]),
            "trace_id": req.trace_id,
            "run_overrides": {"retry_backoff_s": 0.0},
        }
    return fn


def _start_router(workdir: str, n_replicas: int, autoscale: bool = False):
    from land_trendr_tpu.fleet import FleetRouter, RouterConfig

    cfg = RouterConfig(
        workdir=workdir,
        spawn_replicas=n_replicas,
        health_interval_s=0.5,
        route_queue_depth=512,
        tenant_quota=256,
        route_retries=3,
        decision_log=True,
        replica_args=("--feed-cache-mb", "64"),
        **(
            {
                "autoscale": True, "min_replicas": n_replicas,
                "max_replicas": n_replicas + 2, "scale_hold_s": 0.5,
            }
            if autoscale else {}
        ),
    )
    router = FleetRouter(cfg)
    thread = threading.Thread(
        target=router.serve_forever, name=f"capacity-{Path(workdir).name}"
    )
    thread.start()
    return router, thread


def run_curve_leg(
    root: Path, stack_dir: str, tile: int, n_replicas: int,
    qps_steps: "list[float]", window_s: float, timeout_s: float,
    seed: int,
) -> "tuple[dict, ReplayReport]":
    """One replica count's curve: a fresh fleet, one open-loop phase
    per offered rate (the fleet drains between phases — the runner
    polls every request to terminal), every cell assembled through the
    trace store, then the leg's decision log replayed."""
    workdir = str(root / f"rt_{n_replicas}r")
    router, thread = _start_router(workdir, n_replicas)
    points: "list[dict]" = []
    try:
        for step, qps in enumerate(qps_steps):
            cfg = LoadConfig(
                mode="open", duration_s=window_s, qps=qps,
                workers=4, seed=seed + step, tenants=3,
                tenant_skew=1.0, wave_amp=0.3,
                wave_period_s=max(window_s, 1.0),
                timeout_s=timeout_s,
            )
            runner = LoadRunner(
                cfg, InProcClient(router), _payload_fn(stack_dir, tile),
                telemetry=router.telemetry,
            )
            report = runner.run(phase=f"r{n_replicas}_q{qps}")
            sweep = assemble_sweep(workdir, report.trace_ids)
            lat = sweep["latencies"]
            point = {
                "replicas": n_replicas,
                "offered_qps": qps,
                "achieved_qps": round(report.done / max(report.wall_s, 1e-6), 4),
                "p50_s": round(percentile(lat, 50.0), 4),
                "p99_s": round(percentile(lat, 99.0), 4),
                "goodput_qps": round(report.done / max(report.wall_s, 1e-6), 4),
                "done": report.done,
                "failed": report.failed,
                "rejected": report.rejected,
                "assembled": sweep["assembled"],
                "window_s": round(report.wall_s, 3),
                "blame": sweep["blame"],
            }
            points.append(point)
            if router.telemetry is not None:
                router.telemetry.sweep_point(**{
                    k: v for k, v in point.items() if k != "blame"
                })
        knee_idx = mark_knee(points)
        if knee_idx is None and points:
            # no interior knee in the measured range: the saturation
            # point stands in (stamped so every curve names a blame)
            knee_idx = len(points) - 1
            points[knee_idx]["knee"] = True
            points[knee_idx]["knee_blame"] = dominant_blame(
                points[knee_idx].get("blame") or {}
            )
        if knee_idx is not None and router.telemetry is not None:
            p = points[knee_idx]
            router.telemetry.sweep_point(**{
                k: v for k, v in p.items() if k != "blame"
            })
    finally:
        router.stop()
        thread.join(timeout=300)
    replay = replay_decisions(os.path.join(workdir, DECISIONS_NAME))
    curve = {
        "replicas": n_replicas,
        "points": points,
        "knee_index": knee_idx,
        "knee_offered_qps": (
            points[knee_idx]["offered_qps"] if knee_idx is not None else None
        ),
        "knee_blame": (
            points[knee_idx].get("knee_blame")
            if knee_idx is not None else None
        ),
        "replay": replay.to_json(),
    }
    return curve, replay


def run_autoscale_leg(
    root: Path, stack_dir: str, tile: int, timeout_s: float, seed: int,
) -> "tuple[dict, ReplayReport]":
    """An autoscaled fleet under closed-loop load with a scripted burn
    history driven through ``scale_tick`` — the leg that puts REAL
    autoscale records (with real spawns/drains behind them) into the
    decision log the replay must reproduce."""
    workdir = str(root / "rt_autoscale")
    router, thread = _start_router(workdir, 1, autoscale=True)
    try:
        cfg = LoadConfig(
            mode="closed", duration_s=6.0, requests=8, workers=2,
            seed=seed, tenants=2, timeout_s=timeout_s,
        )
        runner = LoadRunner(
            cfg, InProcClient(router), _payload_fn(stack_dir, tile),
            telemetry=router.telemetry,
        )
        done = {}

        def _drive() -> None:
            done["report"] = runner.run(phase="autoscale")

        t = threading.Thread(target=_drive)
        t.start()
        # scripted burn history: pressure up, hold, release — recorded
        # decisions include real up/down actions between the bounds
        # (wall clock: the decision log's one time domain)
        now = time.time()
        script = [0.9, 0.9, 0.9, 0.7, 0.4, 0.02, 0.02, 0.02, 0.02]
        for i, burn in enumerate(script):
            router.scale_tick(burn, now + i * 0.7)
            time.sleep(0.7)
        t.join(timeout=timeout_s + 60)
        report = done.get("report")
    finally:
        router.stop()
        thread.join(timeout=300)
    replay = replay_decisions(os.path.join(workdir, DECISIONS_NAME))
    leg = {
        "done": report.done if report else None,
        "failed": report.failed if report else None,
        "scripted_burns": len(script),
        "replay": replay.to_json(),
    }
    return leg, replay


def run_bench(
    smoke: bool, root: str, size: int, years: int, tile: int,
) -> dict:
    rootp = Path(root)
    stack_dir = _write_scene(rootp, size, years)
    replica_counts = [1, 2] if smoke else [1, 2, 3]
    qps_steps = [0.5, 1.0, 2.0] if smoke else [0.5, 1.0, 2.0, 4.0]
    window_s = 5.0 if smoke else 15.0
    timeout_s = 120.0 if smoke else 240.0

    curves: "list[dict]" = []
    replays: "list[ReplayReport]" = []
    for i, n in enumerate(replica_counts):
        curve, replay = run_curve_leg(
            rootp, stack_dir, tile, n, qps_steps, window_s, timeout_s,
            seed=100 + 10 * i,
        )
        curves.append(curve)
        replays.append(replay)

    autoscale_leg, as_replay = run_autoscale_leg(
        rootp, stack_dir, tile, timeout_s, seed=7
    )
    replays.append(as_replay)

    # the clock-free speedup proof: a scripted 2-minute-span history
    # replayed in milliseconds (live-leg spans are short by design, so
    # their speedup_x is bounded by the bench budget, not the machine)
    script_path = str(rootp / "scripted_decisions.jsonl")
    write_scripted_history(script_path, seed=23, events=2000)
    scripted = replay_decisions(script_path)

    live_decisions = sum(r.decisions for r in replays)
    live_matched = sum(r.matched for r in replays)
    replay_summary = {
        "decisions": live_decisions,
        "matched": live_matched,
        "match": bool(live_decisions > 0 and live_matched == live_decisions),
        "speedup_x": round(
            min((r.speedup_x for r in replays if r.decisions), default=0.0),
            3,
        ),
        "legs": len(replays),
    }
    invariants = {
        "curves_all_counts": len(curves) == len(replica_counts),
        "points_per_curve": all(
            len(c["points"]) == len(qps_steps) for c in curves
        ),
        "knee_named_per_curve": all(
            c["knee_blame"] is not None for c in curves
        ),
        "live_replay_match": replay_summary["match"],
        "scripted_replay_match": scripted.match,
        "scripted_replay_100x": scripted.speedup_x >= 100.0,
        "every_cell_assembled": all(
            p["assembled"] > 0 for c in curves for p in c["points"]
        ),
    }
    report = {
        "schema": REPORT_SCHEMA,
        "smoke": smoke,
        "workload": {
            "scene_px": size * size,
            "years": years,
            "tile_size": tile,
            "replica_counts": replica_counts,
            "qps_steps": qps_steps,
            "window_s": window_s,
            "mode": "open",
            "wave_amp": 0.3,
        },
        "curves": curves,
        "autoscale_leg": autoscale_leg,
        "replay": replay_summary,
        "scripted_replay": scripted.to_json(),
        "invariants": invariants,
        "ok": all(invariants.values()),
    }
    schema_errs = validate_report(report)
    report["invariants"]["schema_valid"] = not schema_errs
    if schema_errs:
        report["schema_errors"] = schema_errs
        report["ok"] = False
    return report


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="minutes-scale gate mode (2 replica counts, 3 "
                    "QPS steps, short windows)")
    ap.add_argument("--size", type=int, default=None,
                    help="scene edge px (default: 40 smoke / 48 full)")
    ap.add_argument("--years", type=int, default=None,
                    help="stack years (default: 7)")
    ap.add_argument("--tile", type=int, default=None,
                    help="tile size (default: 20 smoke / 24 full)")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the bench workdirs under DIR")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    size = args.size or (40 if args.smoke else 48)
    years = args.years or 7
    tile = args.tile or (20 if args.smoke else 24)

    root = args.keep or tempfile.mkdtemp(prefix="lt_capacity_bench_")
    Path(root).mkdir(parents=True, exist_ok=True)
    try:
        report = run_bench(args.smoke, root, size, years, tile)
    finally:
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(json.dumps({
        "ok": report["ok"],
        "knees": {
            str(c["replicas"]): {
                "offered_qps": c["knee_offered_qps"],
                "blame": c["knee_blame"],
            }
            for c in report["curves"]
        },
        "replay_match": report["replay"]["match"],
        "scripted_speedup_x": report["scripted_replay"]["speedup_x"],
    }, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
