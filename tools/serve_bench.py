"""Serve-mode cold-vs-warm bench: the headline number of service mode.

Starts a real :class:`~land_trendr_tpu.serve.server.SegmentationServer`
(loopback HTTP, shared ingest store, RAM cache tier OFF so every demand
read consults the store), submits the SAME lazy-ingest job twice over
the API, and measures client-side latency submit → terminal:

* the **cold** job pays jit compile (the program-cache miss compiles the
  whole upload→dispatch→fetch program chain) AND TIFF decode (the store
  ingests every block it decodes);
* the **warm** job must run **zero jit compiles** (program-cache hit —
  ``program_cache.misses == 0``) and **zero TIFF decodes** (every block
  served from the ingest store — ``ingest_store.misses == 0``), the
  structural invariants ``tools/perf_gate.py`` asserts against this
  bench's ``--smoke`` artifact.

Artifacts are digest-compared across the two job workdirs (warm ≡ cold,
byte-identical), so the speedup is never bought with correctness.

    python tools/serve_bench.py --smoke --out /tmp/serve_smoke.json
    python tools/serve_bench.py --out SERVE_r11.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402


def _digest_workdir(workdir: str) -> dict:
    """tile_id → {array name → sha256} (array-content identity, like
    fault_soak: npz zip metadata legitimately differs run to run)."""
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


def _post(port: int, path: str, payload: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as r:
        return json.loads(r.read())


def _await_terminal(server, job_id: str, timeout_s: float) -> dict:
    """Poll over HTTP; fall back to the in-process job table when the
    API is already shutting down (a ``max_jobs`` server closes its
    socket right after the last job goes terminal — losing the race to
    one final GET is not a bench failure)."""
    from land_trendr_tpu.serve import TERMINAL_STATES

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            snap = _get(server.port, f"/jobs/{job_id}")
        except (urllib.error.URLError, ConnectionError, OSError):
            snap = server.job_status(job_id)
        if snap is not None and snap["state"] in TERMINAL_STATES:
            return snap
        time.sleep(0.05)
    raise TimeoutError(f"job {job_id} not terminal within {timeout_s}s")


def _job_leg(server, request: dict, timeout_s: float) -> tuple[dict, float]:
    """Submit one job over the API, await its terminal state; returns
    (terminal snapshot, client-side submit→terminal latency seconds)."""
    t0 = time.perf_counter()
    status, snap = _post(server.port, "/jobs", request)
    if status != 200:
        raise RuntimeError(f"submission failed ({status}): {snap}")
    snap = _await_terminal(server, snap["job_id"], timeout_s)
    latency = time.perf_counter() - t0
    if snap["state"] != "done":
        raise RuntimeError(
            f"job {snap['job_id']} ended {snap['state']}: "
            f"{snap.get('error')}"
        )
    return snap, latency


def run_bench(size: int, years: int, tile: int, root: str) -> dict:
    from land_trendr_tpu.io.synthetic import (
        SceneSpec,
        make_stack,
        write_stack_c2,
    )
    from land_trendr_tpu.serve import SegmentationServer, ServeConfig

    stack_dir = str(Path(root) / "stack")
    write_stack_c2(
        stack_dir,
        make_stack(
            SceneSpec(
                width=size,
                height=size,
                year_start=2000,
                year_end=2000 + years - 1,
                seed=11,
            )
        ),
    )

    cfg = ServeConfig(
        workdir=str(Path(root) / "serve"),
        serve_port=0,
        max_jobs=2,
        # RAM tier OFF: every demand read consults the persistent store,
        # so the warm leg's zero-decode claim is structural, not an
        # artifact of RAM caching (the store tier is what survives a
        # server restart)
        feed_cache_mb=0,
        ingest_store_mb=256,
    )
    server = SegmentationServer(cfg)
    request = {
        "stack_dir": stack_dir,
        "tile_size": tile,
        "lazy": True,
        "params": {"max_segments": 4, "vertex_count_overshoot": 2},
    }
    legs: dict = {}
    errors: list = []

    def drive() -> None:
        try:
            for leg in ("cold", "warm"):
                snap, latency = _job_leg(server, request, 600.0)
                legs[leg] = {"snap": snap, "latency_s": latency}
        except Exception as e:  # surfaces in the report, fails the bench
            errors.append(f"{type(e).__name__}: {e}")
            server.stop()

    t = threading.Thread(target=drive, name="serve-bench-client")
    t.start()
    server.serve_forever()  # drains both jobs, then shuts down
    t.join(timeout=30)
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")

    def leg_report(leg: str) -> dict:
        snap = legs[leg]["snap"]
        summary = snap["summary"]
        return {
            "latency_s": round(legs[leg]["latency_s"], 4),
            "job_wall_s": round(
                snap["finished_t"] - snap["submitted_t"], 4
            ),
            "run_wall_s": summary["wall_s"],
            "program_cache": summary["program_cache"],
            "ingest_store": summary.get("ingest_store"),
        }

    cold, warm = leg_report("cold"), leg_report("warm")
    parity_ok = bool(
        _digest_workdir(legs["cold"]["snap"]["workdir"])
        == _digest_workdir(legs["warm"]["snap"]["workdir"])
    ) and bool(_digest_workdir(legs["cold"]["snap"]["workdir"]))
    warm_store = warm["ingest_store"] or {}
    report = {
        "workload": {
            "scene_px": size * size,
            "years": years,
            "tile_size": tile,
            "tiles": (size // tile) ** 2,
            "lazy": True,
            "ingest_store_mb": cfg.ingest_store_mb,
            "feed_cache_mb": cfg.feed_cache_mb,
        },
        "cold": cold,
        "warm": warm,
        # the headline: a warm job skips compile AND decode
        "speedup_warm": round(cold["latency_s"] / warm["latency_s"], 2)
        if warm["latency_s"]
        else None,
        "invariants": {
            "warm_zero_compiles": warm["program_cache"]["misses"] == 0,
            "warm_zero_decodes": warm_store.get("misses", -1) == 0
            and warm_store.get("hits", 0) > 0,
            "cold_compiled": cold["program_cache"]["misses"] == 1,
        },
        "parity_ok": parity_ok,
    }
    return report


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale tier-1 mode (tiny scene)")
    ap.add_argument("--size", type=int, default=None,
                    help="scene edge px (default: 64 smoke / 256 full)")
    ap.add_argument("--years", type=int, default=None,
                    help="stack years (default: 7 smoke / 16 full)")
    ap.add_argument("--tile", type=int, default=None,
                    help="tile size (default: 32 smoke / 64 full)")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the bench workdirs under DIR")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    size = args.size or (64 if args.smoke else 256)
    years = args.years or (7 if args.smoke else 16)
    tile = args.tile or (32 if args.smoke else 64)

    root = args.keep or tempfile.mkdtemp(prefix="lt_serve_bench_")
    Path(root).mkdir(parents=True, exist_ok=True)
    try:
        report = run_bench(size, years, tile, root)
    finally:
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    ok = report["parity_ok"] and all(report["invariants"].values())
    report["ok"] = ok
    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(
        json.dumps(
            {
                "ok": ok,
                "cold_s": report["cold"]["latency_s"],
                "warm_s": report["warm"]["latency_s"],
                "speedup_warm": report["speedup_warm"],
                "invariants": report["invariants"],
                "parity_ok": report["parity_ok"],
            }
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
