"""Fold driver event logs into a per-stage report + chrome://tracing export.

The post-hoc consumer of the :mod:`land_trendr_tpu.obs` event stream: give
it one or more ``events.jsonl`` files (several = one multihost run's
per-process files) and it emits

* a JSON **report** on stdout — per-event-type counts, tile compute-latency
  and px/s distributions, retry/failure totals, backlog-depth maxima, the
  run_done stage split, the feed-cache rollup (hits/misses/decode seconds
  with a derived hit rate), the fetch and upload rollups (transfers/bytes
  and the pack/wait/unpack split, with derived ``transfers_per_tile`` and
  ``effective_gb_per_s`` — wire bytes over blocking wait seconds), the
  ingest-store rollup (store hits/puts with a derived hit rate), the
  per-tenant SLO rollup (p50/p95/p99 queue-wait and exec latency,
  deadline hit-rate — from ``job_slo`` events), the request-tracing
  rollup (end-to-end latency distribution, re-route counts, p50/p95/p99
  per blame component — from ``request_done``), the cross-job batching
  rollup (launch/job/tile totals, jobs-per-launch, occupancy and
  window-wait distributions — from ``batch_launch``/``batch_demux``),
  the resource high-water
  section (RSS / fd / thread / backlog watermarks from the flight
  sampler's ``flight_sample`` series), and per-host rollups — schema
  lint and fold run in a SINGLE pass per file
  (``fold(paths, schema_errors=...)``);
* with ``--trace OUT.json``, a **Chrome trace-event file** (the
  ``chrome://tracing`` / Perfetto JSON array format): per-tile device-wait
  and artifact-write slices, retry instants, backlog counter tracks, and
  the flight sampler's counter tracks (``resources``: RSS/threads/fds;
  ``sampler_backlog``: pipeline backlogs + queue depth), one trace
  "process" per event file — so the driver's host-side phases line up
  next to the device traces ``utils/profiling.trace`` captures.

Timeline construction: every event carries wall + monotonic clocks; each
run scope (a ``run_start`` and what follows it) anchors its monotonic
clock to its ``run_start`` wall time, so durations stay
monotonic-accurate while multiple processes align on the wall clock.

Usage:
    python tools/obs_report.py WORKDIR | EVENTS.jsonl ... [--trace out.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.obs.events import (  # noqa: E402
    expand_event_paths,
    run_scope_reset,
    validate_event,
)
from land_trendr_tpu.obs.spans import (  # noqa: E402
    busy_union_s,
    tail_ratio,
)

_US = 1e6  # trace-event timestamps are microseconds


def _stats(values: list[float]) -> dict | None:
    if not values:
        return None
    v = sorted(values)

    def q(p: float) -> float:
        return v[min(len(v) - 1, int(p * len(v)))]

    return {
        "n": len(v),
        "min": round(v[0], 6),
        "p50": round(q(0.50), 6),
        "mean": round(sum(v) / len(v), 6),
        "p95": round(q(0.95), 6),
        "p99": round(q(0.99), 6),
        "max": round(v[-1], 6),
    }


def _wall_anchored(scopes: list[dict], rec: dict) -> float:
    """Event time on the shared wall axis, with monotonic-clock accuracy.

    Uses the current run scope's (wall, mono) anchor pair; events before
    any ``run_start`` (malformed streams) fall back to their own wall time.
    """
    if scopes:
        a = scopes[-1]
        return a["t_wall"] + (rec["t_mono"] - a["t_mono"])
    return rec.get("t_wall", 0.0)


def _mono_anchored(scopes: list[dict], mono: float, fallback: float) -> float:
    """A raw monotonic-clock value (a ``span`` event's start/end) on the
    same wall axis as :func:`_wall_anchored`."""
    if scopes:
        a = scopes[-1]
        return a["t_wall"] + (mono - a["t_mono"])
    return fallback


def _fresh_scope() -> dict:
    return {
        "counts": {}, "compute_s": [], "px_per_s": [], "record_s": [],
        "pixels": 0, "max_feed_backlog": 0, "max_write_backlog": 0,
        "retries": 0, "failures": 0, "quarantined": 0, "faults_injected": 0,
        "stalls": 0, "stragglers": 0, "tiles_leased": 0, "tiles_stolen": 0,
        "tiles_speculated": 0, "stage_s": {}, "span_s": {},
        "intervals": [], "feed_cache": None,
        "fetch": None, "upload": None, "ingest_store": None,
        "serve": None, "program_cache": None,
        "slo": None, "resources": None, "router": None, "tune": None,
        "request": None, "batching": None,
    }


def _slo_scope(cur: dict) -> dict:
    """The lazily-created per-tenant SLO sub-aggregate of one scope
    (fed by ``job_slo`` events — the serve layer's accounting stream)."""
    if cur["slo"] is None:
        cur["slo"] = {}
    return cur["slo"]


def _slo_tenant(slo: dict, tenant: str) -> dict:
    t = slo.get(tenant)
    if t is None:
        t = slo[tenant] = {
            "queue_wait_s": [], "exec_s": [], "met": 0, "missed": 0,
            "with_deadline": 0,
        }
    return t


#: flight_sample gauges folded into the resource high-water section,
#: name → report key (each merges as a maximum — watermarks)
_RESOURCE_HIGHWATER = {
    "rss_bytes": "rss_bytes_max",
    "open_fds": "open_fds_max",
    "threads": "threads_max",
    "feed_backlog": "feed_backlog_max",
    "write_backlog": "write_backlog_max",
    "fetch_backlog": "fetch_backlog_max",
    "upload_backlog": "upload_backlog_max",
    "queue_depth": "queue_depth_max",
    "cache_bytes": "cache_bytes_max",
    "store_bytes": "store_bytes_max",
    "device_bytes_in_use": "device_bytes_max",
}


def _resources_scope(cur: dict) -> dict:
    if cur["resources"] is None:
        cur["resources"] = {"samples": 0}
    return cur["resources"]


def _serve_scope(cur: dict) -> dict:
    """The lazily-created serve sub-aggregate of one scope (the server's
    own events file carries the job lifecycle; job run scopes carry only
    their program_cache verdicts)."""
    if cur["serve"] is None:
        cur["serve"] = {
            "submitted": 0, "rejected": 0, "by_status": {},
            "wait_s": [], "job_s": [],
        }
    return cur["serve"]


def _router_scope(cur: dict) -> dict:
    """The lazily-created router sub-aggregate of one scope (the fleet
    router's own events file carries the routing plane)."""
    if cur["router"] is None:
        cur["router"] = {
            "routed": 0, "warm": 0, "rerouted": 0, "throttled": {},
            "replicas_up": 0, "replicas_down": {}, "scales": {},
            "queue_wait_s": [],
            # crash-safe control plane (fleet/journal): durable appends
            # and the restart-recovery splits
            "journal_appends": 0, "recovery": {},
        }
    return cur["router"]


def _merge_router(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the routing-plane rollups (None when no
    file's last scope carried router events); derives the warm-route
    ratio and the queue-wait distribution."""
    seen = [c["router"] for c in folded if c["router"] is not None]
    if not seen:
        return None
    throttled: dict = {}
    downs: dict = {}
    scales: dict = {}
    recovery: dict = {}
    for s in seen:
        for k, v in s["throttled"].items():
            throttled[k] = throttled.get(k, 0) + v
        for k, v in s["replicas_down"].items():
            downs[k] = downs.get(k, 0) + v
        for k, v in s["scales"].items():
            scales[k] = scales.get(k, 0) + v
        for k, v in s.get("recovery", {}).items():
            recovery[k] = recovery.get(k, 0) + v
    routed = sum(s["routed"] for s in seen)
    warm = sum(s["warm"] for s in seen)
    return {
        "routed": routed,
        "warm": warm,
        "warm_ratio": round(warm / routed, 4) if routed else None,
        "rerouted": sum(s["rerouted"] for s in seen),
        "throttled": dict(sorted(throttled.items())),
        "replicas_up": sum(s["replicas_up"] for s in seen),
        "replicas_down": dict(sorted(downs.items())),
        "scales": dict(sorted(scales.items())),
        "queue_wait_s": _stats([v for s in seen for v in s["queue_wait_s"]]),
        "journal_appends": sum(s.get("journal_appends", 0) for s in seen),
        "recovery": dict(sorted(recovery.items())) or None,
    }


def _request_scope(cur: dict) -> dict:
    """The lazily-created request-tracing sub-aggregate of one scope
    (fed by ``request_done`` — the router's terminal request records)."""
    if cur["request"] is None:
        cur["request"] = {
            "latency_s": [], "rerouted": 0, "by_status": {},
            "blame": {},
        }
    return cur["request"]


def _merge_request(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the request-tracing rollups (None when no
    file's last scope carried a ``request_done``): the end-to-end
    latency distribution, re-route counts, and p50/p95/p99 per blame
    component — "where do slow requests spend their time", fleet-wide,
    straight from the stream."""
    seen = [c["request"] for c in folded if c["request"] is not None]
    if not seen:
        return None
    by_status: dict = {}
    blame: dict = {}
    for s in seen:
        for k, v in s["by_status"].items():
            by_status[k] = by_status.get(k, 0) + v
        for comp, vals in s["blame"].items():
            blame.setdefault(comp, []).extend(vals)
    lats = [v for s in seen for v in s["latency_s"]]
    return {
        "requests": len(lats),
        "rerouted": sum(s["rerouted"] for s in seen),
        "by_status": dict(sorted(by_status.items())),
        "latency_s": _stats(lats),
        "by_component": {
            comp: _stats(vals) for comp, vals in sorted(blame.items())
        },
    }


def _merge_serve(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the serve job-lifecycle rollups (None when no
    file's last scope carried any job events); derives queue-wait and
    job-latency distributions."""
    seen = [c["serve"] for c in folded if c["serve"] is not None]
    if not seen:
        return None
    by_status: dict = {}
    for s in seen:
        for k, v in s["by_status"].items():
            by_status[k] = by_status.get(k, 0) + v
    return {
        "submitted": sum(s["submitted"] for s in seen),
        "rejected": sum(s["rejected"] for s in seen),
        "by_status": dict(sorted(by_status.items())),
        "queue_wait_s": _stats([v for s in seen for v in s["wait_s"]]),
        "job_s": _stats([v for s in seen for v in s["job_s"]]),
    }


def _merge_slo(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the per-tenant SLO aggregates (None when no
    file's last scope carried a ``job_slo``): per-tenant p50/p95/p99
    queue-wait and exec latency plus the deadline hit-rate (over jobs
    that SET a deadline; jobs without one count as met overall)."""
    seen = [c["slo"] for c in folded if c["slo"] is not None]
    if not seen:
        return None
    by_tenant: dict = {}
    for s in seen:
        for tenant, t in s.items():
            agg = by_tenant.setdefault(
                tenant,
                {"queue_wait_s": [], "exec_s": [], "met": 0, "missed": 0,
                 "with_deadline": 0},
            )
            agg["queue_wait_s"].extend(t["queue_wait_s"])
            agg["exec_s"].extend(t["exec_s"])
            for k in ("met", "missed", "with_deadline"):
                agg[k] += t[k]
    out: dict = {"by_tenant": {}}
    tot_met = tot_missed = tot_deadline = 0
    for tenant in sorted(by_tenant):
        t = by_tenant[tenant]
        tot_met += t["met"]
        tot_missed += t["missed"]
        tot_deadline += t["with_deadline"]
        out["by_tenant"][tenant] = {
            "jobs": t["met"] + t["missed"],
            "queue_wait_s": _stats(t["queue_wait_s"]),
            "exec_s": _stats(t["exec_s"]),
            # deadline-scoped: ``met`` on a no-deadline job is true by
            # definition, so the hit rate divides over jobs that HAD a
            # deadline (a miss implies one) — 99 no-deadline jobs must
            # not dilute one missed deadline into a 0.99 hit rate
            "deadline": {
                "with_deadline": t["with_deadline"],
                "met": t["with_deadline"] - t["missed"],
                "missed": t["missed"],
                "hit_rate": (
                    round(
                        (t["with_deadline"] - t["missed"])
                        / t["with_deadline"],
                        4,
                    )
                    if t["with_deadline"] else None
                ),
            },
        }
    out["jobs"] = tot_met + tot_missed
    out["missed"] = tot_missed
    out["hit_rate"] = (
        round((tot_deadline - tot_missed) / tot_deadline, 4)
        if tot_deadline else None
    )
    return out


def _merge_resources(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the flight-sampler high-water sections (None
    when no file's last scope carried a ``flight_sample``): every gauge
    merges as a maximum — the resource watermark the run actually hit."""
    seen = [c["resources"] for c in folded if c["resources"] is not None]
    if not seen:
        return None
    out: dict = {"samples": sum(s["samples"] for s in seen)}
    for key in _RESOURCE_HIGHWATER.values():
        vals = [s[key] for s in seen if key in s]
        if vals:
            out[key] = max(vals)
    return out


def _tune_scope(cur: dict) -> dict:
    """The lazily-created autotuner sub-aggregate of one scope (fed by
    ``tune_probe`` / ``tune_profile`` — `lt tune` scopes and any run
    whose config resolved "auto" knobs)."""
    if cur["tune"] is None:
        cur["tune"] = {
            "groups_probed": 0, "groups_skipped": 0, "probes": 0,
            "best_speedup": None, "profile": None,
        }
    return cur["tune"]


def _merge_tune(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the autotuner rollups (None when no file's
    last scope carried one): probe totals summed, the best observed
    group speedup, and the profile verdicts by source (how many scopes
    ran store-warm vs freshly probed vs untuned defaults)."""
    seen = [c["tune"] for c in folded if c["tune"] is not None]
    if not seen:
        return None
    speedups = [
        s["best_speedup"] for s in seen if s["best_speedup"] is not None
    ]
    by_source: dict[str, int] = {}
    keys: set = set()
    for s in seen:
        p = s["profile"]
        if p is not None:
            by_source[p["source"]] = by_source.get(p["source"], 0) + 1
            if p.get("key"):
                keys.add(p["key"])
    return {
        "groups_probed": sum(s["groups_probed"] for s in seen),
        "groups_skipped": sum(s["groups_skipped"] for s in seen),
        "probes": sum(s["probes"] for s in seen),
        "best_speedup": max(speedups) if speedups else None,
        "profiles_by_source": by_source,
        "profile_keys": sorted(keys),
    }


def _batching_scope(cur: dict) -> dict:
    """The lazily-created cross-job-batching sub-aggregate of one scope
    (fed by ``batch_launch`` / ``batch_demux`` — the serve dispatcher's
    coalescing stream)."""
    if cur["batching"] is None:
        cur["batching"] = {
            "launches": 0, "jobs": 0, "tiles": 0, "padded_px": 0,
            "occupancy": [], "window_wait_s": [], "demuxed_tiles": 0,
            "demuxed_members": 0,
        }
    return cur["batching"]


def _merge_batching(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the cross-job-batching rollups (None when no
    file's last scope carried a batch event): launch/job/tile totals,
    the occupancy and window-wait distributions, and the derived
    ``jobs_per_launch`` — how much per-launch overhead the coalescing
    actually amortised."""
    seen = [c["batching"] for c in folded if c["batching"] is not None]
    if not seen:
        return None
    launches = sum(s["launches"] for s in seen)
    jobs = sum(s["jobs"] for s in seen)
    return {
        "launches": launches,
        "jobs": jobs,
        "jobs_per_launch": round(jobs / launches, 2) if launches else None,
        "tiles": sum(s["tiles"] for s in seen),
        "padded_px": sum(s["padded_px"] for s in seen),
        "occupancy": _stats([v for s in seen for v in s["occupancy"]]),
        "window_wait_s": _stats(
            [v for s in seen for v in s["window_wait_s"]]
        ),
        "demuxed_tiles": sum(s["demuxed_tiles"] for s in seen),
        "demuxed_members": sum(s["demuxed_members"] for s in seen),
    }


def _merge_program_cache(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the warm-program-cache rollups (one per job
    run scope, plus the server's terminal aggregate); adds the derived
    ``hit_rate`` — the fraction of runs that compiled nothing."""
    seen = [
        c["program_cache"] for c in folded if c["program_cache"] is not None
    ]
    if not seen:
        return None
    out = {
        "hits": sum(s["hits"] for s in seen),
        "misses": sum(s["misses"] for s in seen),
        "compile_s": round(sum(s["compile_s"] for s in seen), 4),
    }
    keys = [s["keys"] for s in seen if "keys" in s]
    if keys:
        out["keys"] = max(keys)
    runs = out["hits"] + out["misses"]
    out["hit_rate"] = round(out["hits"] / runs, 4) if runs else None
    return out


#: feed_cache event counters summed across files in the report; the
#: occupancy gauges (cache_bytes/budget_bytes) are point-in-time, so the
#: merge takes their maximum instead
_FEED_CACHE_COUNTERS = (
    "hits", "misses", "evictions", "decode_s", "inserted_bytes",
    "readahead_blocks", "readahead_hits", "readahead_dropped",
)
_FEED_CACHE_GAUGES = ("cache_bytes", "budget_bytes")


def _merge_feed_cache(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the per-scope feed_cache rollups (None when no
    file's last scope carried one); adds the derived ``hit_rate``."""
    seen = [c["feed_cache"] for c in folded if c["feed_cache"] is not None]
    if not seen:
        return None
    out: dict = {}
    for k in _FEED_CACHE_COUNTERS:
        vals = [fc[k] for fc in seen if k in fc]
        if vals:
            v = sum(vals)
            out[k] = round(v, 4) if isinstance(v, float) else v
    for k in _FEED_CACHE_GAUGES:
        vals = [fc[k] for fc in seen if k in fc]
        if vals:
            out[k] = max(vals)
    lookups = out.get("hits", 0) + out.get("misses", 0)
    out["hit_rate"] = round(out.get("hits", 0) / lookups, 4) if lookups else None
    return out


#: transfer-rollup counters (fetch AND its upload mirror) summed across
#: files; backlog_max is a per-process high watermark, so the merge
#: takes its maximum
_XFER_COUNTERS = (
    "tiles", "transfers", "bytes", "pack_s", "wait_s", "unpack_s",
)


def _merge_xfer(folded: list[dict], key: str) -> "dict | None":
    """Cross-file merge of the per-scope transfer rollups (``fetch`` or
    ``upload``; None when no file's last scope carried one); derives the
    effective link bandwidth — wire bytes over *blocking* wait seconds,
    i.e. the rate the driver loop actually experienced after async
    overlap — and the per-tile transfer count (packed = 1.0)."""
    seen = [c[key] for c in folded if c[key] is not None]
    if not seen:
        return None
    out: dict = {}
    for k in _XFER_COUNTERS:
        vals = [fx[k] for fx in seen if k in fx]
        if vals:
            v = sum(vals)
            out[k] = round(v, 4) if isinstance(v, float) else v
    blv = [fx["backlog_max"] for fx in seen if "backlog_max" in fx]
    if blv:
        out["backlog_max"] = max(blv)
    pk = {fx.get("packed") for fx in seen if "packed" in fx}
    if len(pk) == 1:
        out["packed"] = pk.pop()
    tiles = out.get("tiles", 0)
    out["transfers_per_tile"] = (
        round(out.get("transfers", 0) / tiles, 2) if tiles else None
    )
    wait = out.get("wait_s", 0)
    out["effective_gb_per_s"] = (
        round(out.get("bytes", 0) / wait / 1e9, 3) if wait else None
    )
    return out


#: ingest_store counters summed across files; occupancy gauges are
#: point-in-time, so the merge takes their maximum
_INGEST_COUNTERS = (
    "hits", "misses", "put_blocks", "put_bytes", "stale_dropped",
    "corrupt_dropped", "evicted_segments",
)
_INGEST_GAUGES = ("bytes", "budget_bytes", "segments")


def _merge_ingest_store(folded: list[dict]) -> "dict | None":
    """Cross-file merge of the per-scope ingest-store rollups (None when
    no file's last scope carried one); adds the derived ``hit_rate`` —
    the fraction of store lookups that skipped TIFF decode entirely."""
    seen = [c["ingest_store"] for c in folded if c["ingest_store"] is not None]
    if not seen:
        return None
    out: dict = {}
    for k in _INGEST_COUNTERS:
        vals = [s[k] for s in seen if k in s]
        if vals:
            out[k] = sum(vals)
    for k in _INGEST_GAUGES:
        vals = [s[k] for s in seen if k in s]
        if vals:
            out[k] = max(vals)
    lookups = out.get("hits", 0) + out.get("misses", 0)
    out["hit_rate"] = round(out.get("hits", 0) / lookups, 4) if lookups else None
    return out


def fold(
    paths: list[str], schema_errors: "dict[str, list[str]] | None" = None
) -> tuple[dict, list[dict]]:
    """Parse event files → (report dict, flat trace-source records).

    The report aggregates describe each file's LAST run scope — a resumed
    file's aborted earlier attempt must not double-count pixels or skew
    the latency distributions (the same "most recent run" semantics as
    ``summarize_events_file``, the run-summary consumer of this stream).
    The TRACE keeps every scope: the timeline of an abort + resume is
    exactly what a post-mortem wants to see.

    ``schema_errors`` (a caller-owned dict) turns on the schema lint IN
    this pass: each file's ``validate_events_file``-equivalent error list
    lands under its path, so the validating CLI parses every line exactly
    once instead of running a lint pass and then a fold pass (the PR-1
    double-parse this replaces).  ``None`` skips linting (the library
    default and ``--no-validate``).

    Trace-source records carry absolute wall-anchored times; the exporter
    rebases them to the earliest event so trace timestamps start near 0.
    Malformed lines and field-incomplete records are counted
    (``malformed``), never fatal — a torn final line of a killed run must
    still fold best-effort.
    """
    malformed = 0
    hosts: list[dict] = []
    spans: list[dict] = []   # trace-source records, ALL scopes
    folded: list[dict] = []  # each file's LAST scope aggregate

    for fileno, path in enumerate(paths):
        errs = (
            None if schema_errors is None else schema_errors.setdefault(path, [])
        )
        scopes: list[dict] = []
        cur = _fresh_scope()
        host_info: dict = {"events_file": path, "process_index": fileno}
        # tile_id (and "job:<id>") -> wall-anchored start
        starts: dict = {}
        any_line = False
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    malformed += 1
                    if errs is not None:
                        errs.append(f"line {i}: malformed JSON ({e})")
                    continue
                if errs is not None:
                    if not any_line and isinstance(rec, dict) and rec.get("ev") != "run_start":
                        errs.append(
                            f"line {i}: first event is {rec.get('ev')!r}, "
                            "expected 'run_start'"
                        )
                    errs.extend(validate_event(rec, lineno=i))
                any_line = True
                if not isinstance(rec, dict) or not isinstance(rec.get("ev"), str):
                    # torn/foreign JSON that still parsed (e.g. a truncated
                    # prefix that happens to be valid) is malformed, not an
                    # event type of its own
                    malformed += 1
                    continue
                ev = rec["ev"]
                # required fields are read into locals FIRST, aggregates
                # mutated only after they all resolved: a field-incomplete
                # record must count as malformed alone, never half-fold (a
                # tile_done missing px_per_s must not leave its compute_s in
                # the stats and be double-counted under event_counts too)
                try:
                    tw = _wall_anchored(scopes, rec)
                    if ev == "run_start":
                        t_wall, t_mono = rec["t_wall"], rec["t_mono"]
                        scopes.append({"t_wall": t_wall, "t_mono": t_mono})
                        tw = t_wall
                        cur = _fresh_scope()  # aggregates describe the LAST scope
                        starts.clear()
                        # a previous scope's run_done must not leak into this
                        # scope's rollup — run_scope_reset is the SHARED
                        # reset contract with summarize_events_file
                        host_info.update(
                            run_scope_reset(rec, default_process_index=fileno),
                            impl=rec.get("impl"),
                            mesh_devices=rec.get("mesh_devices"),
                        )
                    elif ev == "span":
                        # per-tile stage span (obs/spans): start/end are
                        # monotonic values on the scope's anchor clock
                        name, tile_id = rec["name"], rec["tile_id"]
                        s0, s1 = rec["start"], rec["end"]
                        dur = max(s1 - s0, 0.0)
                        t0 = _mono_anchored(scopes, s0, tw - dur)
                        cur["span_s"][name] = (
                            cur["span_s"].get(name, 0.0) + dur
                        )
                        cur["intervals"].append((t0, t0 + dur))
                        spans.append({
                            "kind": "slice", "file": fileno,
                            "tid": str(name), "name": f"tile {tile_id}",
                            "t0": t0, "dur": dur,
                            "args": {"attempt": rec.get("attempt")},
                        })
                    elif ev == "tile_straggler":
                        tile_id = rec["tile_id"]
                        cur["stragglers"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno,
                            "tid": "device-wait",
                            "name": f"STRAGGLER tile {tile_id}", "t0": tw,
                            "args": {
                                "duration_s": rec.get("duration_s"),
                                "threshold_s": rec.get("threshold_s"),
                                "in_flight": rec.get("in_flight"),
                            },
                        })
                    elif ev == "tile_leased":
                        cur["tiles_leased"] += 1
                    elif ev in ("lease_stolen", "tile_speculated"):
                        # the elastic scheduler acting (runtime/leases):
                        # steal/speculation instants land on the trace
                        # next to the straggler verdicts that drove them
                        tile_id = rec["tile_id"]
                        cur["tiles_leased"] += 1
                        key = (
                            "tiles_stolen" if ev == "lease_stolen"
                            else "tiles_speculated"
                        )
                        cur[key] += 1
                        spans.append({
                            "kind": "instant", "file": fileno,
                            "tid": "device-wait",
                            "name": (
                                f"{'STEAL' if ev == 'lease_stolen' else 'SPECULATE'}"
                                f" tile {tile_id}"
                            ),
                            "t0": tw,
                            "args": {"gen": rec.get("gen")},
                        })
                    elif ev == "tile_start":
                        starts[rec["tile_id"]] = tw
                    elif ev == "tile_done":
                        tile_id, c_s, pps = rec["tile_id"], rec["compute_s"], rec["px_per_s"]
                        cur["compute_s"].append(c_s)
                        cur["px_per_s"].append(pps)
                        cur["pixels"] += rec.get("px", 0)
                        cur["max_feed_backlog"] = max(
                            cur["max_feed_backlog"], rec.get("feed_backlog", 0)
                        )
                        cur["max_write_backlog"] = max(
                            cur["max_write_backlog"], rec.get("write_backlog", 0)
                        )
                        t0 = starts.pop(tile_id, tw - c_s)
                        cur["intervals"].append((t0, t0 + max(c_s, tw - t0)))
                        spans.append({
                            "kind": "slice", "file": fileno, "tid": "device-wait",
                            "name": f"tile {tile_id}", "t0": t0,
                            "dur": max(c_s, tw - t0),
                            "args": {"px": rec.get("px"), "px_per_s": pps},
                        })
                        spans.append({
                            "kind": "counter", "file": fileno, "t0": tw,
                            "name": "backlog",
                            "args": {
                                "feed": rec.get("feed_backlog", 0),
                                "write": rec.get("write_backlog", 0),
                            },
                        })
                    elif ev == "write_done":
                        tile_id, r_s = rec["tile_id"], rec["record_s"]
                        cur["record_s"].append(r_s)
                        cur["intervals"].append((tw - r_s, tw))
                        spans.append({
                            "kind": "slice", "file": fileno, "tid": "write",
                            "name": f"tile {tile_id}",
                            "t0": tw - r_s, "dur": r_s,
                            "args": {"bytes": rec.get("bytes")},
                        })
                    elif ev == "tile_retry":
                        tile_id = rec["tile_id"]
                        cur["retries"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "device-wait",
                            "name": f"retry tile {tile_id}", "t0": tw,
                            "args": {"error": rec.get("error")},
                        })
                    elif ev == "tile_failed":
                        tile_id = rec["tile_id"]
                        cur["failures"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "device-wait",
                            "name": f"FAILED tile {tile_id}", "t0": tw,
                            "args": {"error": rec.get("error")},
                        })
                    elif ev == "tile_quarantined":
                        tile_id = rec["tile_id"]
                        cur["quarantined"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "device-wait",
                            "name": f"QUARANTINED tile {tile_id}", "t0": tw,
                            "args": {"error": rec.get("error")},
                        })
                    elif ev == "fault_injected":
                        cur["faults_injected"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "device-wait",
                            "name": f"fault {rec['seam']}#{rec['index']}",
                            "t0": tw, "args": {"error": rec.get("error")},
                        })
                    elif ev == "stall":
                        cur["stalls"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "device-wait",
                            "name": "STALL", "t0": tw,
                            "args": {
                                "idle_s": rec.get("idle_s"),
                                "timeout_s": rec.get("timeout_s"),
                            },
                        })
                    elif ev == "feed_cache":
                        # the per-run rollup from the feed-decode subsystem
                        # (io/blockcache): required counters must resolve
                        # before the scope keeps it; one per scope, last wins
                        cur["feed_cache"] = {
                            "hits": rec["hits"],
                            "misses": rec["misses"],
                            "evictions": rec["evictions"],
                            "decode_s": rec["decode_s"],
                            **{
                                k: rec[k]
                                for k in (*_FEED_CACHE_COUNTERS, *_FEED_CACHE_GAUGES)
                                if k in rec
                            },
                        }
                    elif ev in ("fetch", "upload"):
                        # device→host fetch rollup (runtime/fetch) and its
                        # host→device upload mirror (runtime/feed): one per
                        # scope, last wins; required counters must resolve
                        cur[ev] = {
                            "tiles": rec["tiles"],
                            "transfers": rec["transfers"],
                            "bytes": rec["bytes"],
                            "pack_s": rec["pack_s"],
                            "wait_s": rec["wait_s"],
                            "unpack_s": rec["unpack_s"],
                            **{
                                k: rec[k]
                                for k in ("backlog_max", "packed")
                                if k in rec
                            },
                        }
                    elif ev == "ingest_store":
                        # persistent ingest-store rollup (io/blockstore):
                        # one per scope, last wins
                        cur["ingest_store"] = {
                            "hits": rec["hits"],
                            "misses": rec["misses"],
                            "put_blocks": rec["put_blocks"],
                            "put_bytes": rec["put_bytes"],
                            **{
                                k: rec[k]
                                for k in (*_INGEST_COUNTERS, *_INGEST_GAUGES)
                                if k in rec
                            },
                        }
                    elif ev == "job_submitted":
                        job_id = rec["job_id"]
                        _serve_scope(cur)["submitted"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": f"submitted {job_id}", "t0": tw,
                            "args": {
                                "tenant": rec.get("tenant"),
                                "queue_depth": rec.get("queue_depth"),
                            },
                        })
                    elif ev == "job_rejected":
                        _serve_scope(cur)["rejected"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": f"REJECTED ({rec['reason']})", "t0": tw,
                            "args": {"queue_depth": rec.get("queue_depth")},
                        })
                    elif ev == "job_start":
                        job_id, w_s = rec["job_id"], rec["wait_s"]
                        _serve_scope(cur)["wait_s"].append(w_s)
                        starts[f"job:{job_id}"] = tw
                    elif ev == "job_done":
                        job_id, w_s = rec["job_id"], rec["wall_s"]
                        sv = _serve_scope(cur)
                        sv["job_s"].append(w_s)
                        status = rec["status"]
                        sv["by_status"][status] = (
                            sv["by_status"].get(status, 0) + 1
                        )
                        t0 = starts.pop(f"job:{job_id}", tw - w_s)
                        spans.append({
                            "kind": "slice", "file": fileno, "tid": "jobs",
                            "name": f"{job_id} [{status}]", "t0": t0,
                            "dur": max(tw - t0, 0.0),
                            "args": {
                                "status": status, "wall_s": w_s,
                                "error": rec.get("error"),
                            },
                        })
                    elif ev == "job_slo":
                        # every field read FIRST: a torn/foreign record
                        # raising mid-branch must not leave itself
                        # half-folded AND counted malformed
                        tenant, qw, ex = (
                            rec["tenant"], rec["queue_wait_s"], rec["exec_s"]
                        )
                        met, slo_job = rec["met"], rec["job_id"]
                        t = _slo_tenant(_slo_scope(cur), tenant)
                        t["queue_wait_s"].append(qw)
                        t["exec_s"].append(ex)
                        t["met" if met else "missed"] += 1
                        if "deadline_s" in rec:
                            t["with_deadline"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": (
                                f"SLO {'met' if met else 'MISSED'} "
                                f"{slo_job}"
                            ),
                            "t0": tw,
                            "args": {
                                "tenant": tenant, "queue_wait_s": qw,
                                "exec_s": ex,
                                "deadline_s": rec.get("deadline_s"),
                            },
                        })
                    elif ev == "flight_sample":
                        # required vitals read FIRST (see job_slo): a
                        # record missing one must not bump the sample
                        # count or the watermarks before it raises
                        rss, thr, fds = (
                            rec["rss_bytes"], rec["threads"],
                            rec["open_fds"],
                        )
                        res = _resources_scope(cur)
                        res["samples"] += 1
                        for name, key in _RESOURCE_HIGHWATER.items():
                            v = rec.get(name)
                            if isinstance(v, (int, float)) and not isinstance(
                                v, bool
                            ):
                                res[key] = max(res.get(key, 0), v)
                        # counter tracks for the sampler series: process
                        # vitals on one track, pipeline backlogs on another
                        spans.append({
                            "kind": "counter", "file": fileno, "t0": tw,
                            "name": "resources",
                            "args": {
                                "rss_mb": round(rss / 1e6, 1),
                                "threads": thr,
                                "open_fds": fds,
                            },
                        })
                        backlogs = {
                            k: rec[k]
                            for k in (
                                "feed_backlog", "write_backlog",
                                "fetch_backlog", "upload_backlog",
                                "queue_depth",
                            )
                            if k in rec
                        }
                        if backlogs:
                            spans.append({
                                "kind": "counter", "file": fileno,
                                "t0": tw, "name": "sampler_backlog",
                                "args": backlogs,
                            })
                    elif ev == "profile_captured":
                        spans.append({
                            "kind": "instant", "file": fileno,
                            "tid": "jobs",
                            "name": (
                                "profile captured" if rec["ok"]
                                else "profile FAILED"
                            ),
                            "t0": tw,
                            "args": {
                                "path": rec.get("path"),
                                "duration_s": rec.get("duration_s"),
                                "error": rec.get("error"),
                            },
                        })
                    elif ev == "route_decision":
                        # routing plane (land_trendr_tpu/fleet): every
                        # field read FIRST (the job_slo discipline)
                        rd_job, replica, warm = (
                            rec["job_id"], rec["replica"], rec["warm"]
                        )
                        rt = _router_scope(cur)
                        rt["routed"] += 1
                        if warm:
                            rt["warm"] += 1
                        if rec.get("attempt", 1) > 1:
                            rt["rerouted"] += 1
                        elif isinstance(
                            rec.get("queue_wait_s"), (int, float)
                        ):
                            rt["queue_wait_s"].append(rec["queue_wait_s"])
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": (
                                f"routed {rd_job} → {replica} "
                                f"[{'warm' if warm else 'cold'}]"
                            ),
                            "t0": tw,
                            "args": {
                                "tenant": rec.get("tenant"),
                                "key": rec.get("key"),
                                "attempt": rec.get("attempt"),
                            },
                        })
                    elif ev == "tenant_throttled":
                        tt_tenant, tt_reason = rec["tenant"], rec["reason"]
                        th = _router_scope(cur)["throttled"]
                        th[tt_reason] = th.get(tt_reason, 0) + 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": f"THROTTLED {tt_tenant} ({tt_reason})",
                            "t0": tw,
                            "args": {"queue_depth": rec.get("queue_depth")},
                        })
                    elif ev == "replica_up":
                        _router_scope(cur)["replicas_up"] += 1
                    elif ev == "replica_down":
                        rd_reason = rec["reason"]
                        dn = _router_scope(cur)["replicas_down"]
                        dn[rd_reason] = dn.get(rd_reason, 0) + 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": (
                                f"replica {rec['replica']} DOWN "
                                f"({rd_reason})"
                            ),
                            "t0": tw,
                            "args": {"inflight": rec.get("inflight")},
                        })
                    elif ev == "scale_decision":
                        sc_dir = rec["direction"]
                        sc = _router_scope(cur)["scales"]
                        sc[sc_dir] = sc.get(sc_dir, 0) + 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": f"scale {sc_dir}",
                            "t0": tw,
                            "args": {
                                "burn": rec.get("burn"),
                                "replicas": rec.get("replicas"),
                            },
                        })
                    elif ev == "journal_append":
                        # crash-safe control plane: one durable
                        # admission-journal commit (counted, not
                        # per-record spanned — the append rate rides
                        # the rollup, not the timeline)
                        _router_scope(cur)["journal_appends"] += 1
                    elif ev == "router_recovered":
                        rv = _router_scope(cur)["recovery"]
                        rv["restarts"] = rv.get("restarts", 0) + 1
                        for k in (
                            "replayed", "relayed", "requeued",
                            "reattached", "deduped",
                        ):
                            v = rec.get(k)
                            if isinstance(v, int) and not isinstance(
                                v, bool
                            ):
                                rv[k] = rv.get(k, 0) + v
                        spans.append({
                            "kind": "instant", "file": fileno,
                            "tid": "jobs",
                            "name": (
                                f"ROUTER RECOVERED "
                                f"({rec.get('replayed', 0)} replayed)"
                            ),
                            "t0": tw,
                            "args": {
                                "relayed": rec.get("relayed"),
                                "requeued": rec.get("requeued"),
                                "reattached": rec.get("reattached"),
                                "deduped": rec.get("deduped"),
                                "recovery_s": rec.get("recovery_s"),
                                "clean": rec.get("clean"),
                            },
                        })
                    elif ev == "request_span":
                        # one router-side segment of a request's
                        # journey (obs/reqtrace): start/end are
                        # monotonic values on the scope's anchor clock
                        rq_name = rec["name"]
                        s0, s1 = rec["start"], rec["end"]
                        dur = max(s1 - s0, 0.0)
                        t0 = _mono_anchored(scopes, s0, tw - dur)
                        cur["intervals"].append((t0, t0 + dur))
                        spans.append({
                            "kind": "slice", "file": fileno,
                            "tid": f"req:{rq_name}",
                            "name": (
                                f"{rec.get('trace_id', '?')} "
                                f"{rq_name}"
                            ),
                            "t0": t0, "dur": dur,
                            "args": {
                                k: rec.get(k)
                                for k in (
                                    "trace_id", "replica", "attempt", "ok",
                                )
                                if rec.get(k) is not None
                            },
                        })
                    elif ev == "request_done":
                        rd_lat, rd_status = (
                            rec["latency_s"], rec["status"]
                        )
                        rq = _request_scope(cur)
                        rq["latency_s"].append(rd_lat)
                        rq["by_status"][rd_status] = (
                            rq["by_status"].get(rd_status, 0) + 1
                        )
                        hops = rec.get("hops")
                        if isinstance(hops, int) and not isinstance(
                            hops, bool
                        ) and hops > 1:
                            rq["rerouted"] += 1
                        bl = rec.get("blame")
                        if isinstance(bl, dict):
                            for comp, v in bl.items():
                                if isinstance(v, (int, float)) and not \
                                        isinstance(v, bool):
                                    rq["blame"].setdefault(
                                        comp, []
                                    ).append(v)
                        spans.append({
                            "kind": "instant", "file": fileno,
                            "tid": "jobs",
                            "name": (
                                f"REQUEST {rd_status} "
                                f"{rec.get('trace_id', '?')}"
                            ),
                            "t0": tw,
                            "args": {
                                "latency_s": rd_lat,
                                "hops": rec.get("hops"),
                                "blame": bl,
                            },
                        })
                    elif ev == "batch_launch":
                        # one coalesced launch (serve/batching): every
                        # field read FIRST (the job_slo discipline)
                        bl_jobs, bl_tiles = rec["jobs"], rec["tiles"]
                        bt = _batching_scope(cur)
                        bt["launches"] += 1
                        bt["jobs"] += bl_jobs
                        bt["tiles"] += bl_tiles
                        bt["padded_px"] += rec.get("padded_px", 0)
                        for k, dst in (
                            ("occupancy", "occupancy"),
                            ("window_wait_s", "window_wait_s"),
                        ):
                            v = rec.get(k)
                            if isinstance(v, (int, float)) and not \
                                    isinstance(v, bool):
                                bt[dst].append(v)
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": (
                                f"BATCH {rec.get('job_id', '?')} "
                                f"x{bl_jobs}"
                            ),
                            "t0": tw,
                            "args": {
                                "jobs": bl_jobs, "tiles": bl_tiles,
                                "occupancy": rec.get("occupancy"),
                                "window_wait_s": rec.get("window_wait_s"),
                            },
                        })
                    elif ev == "batch_demux":
                        bd_tiles = rec["tiles"]
                        bt = _batching_scope(cur)
                        bt["demuxed_tiles"] += bd_tiles
                        bt["demuxed_members"] += 1
                        spans.append({
                            "kind": "instant", "file": fileno, "tid": "jobs",
                            "name": (
                                f"demux {rec.get('job_id', '?')} "
                                f"({bd_tiles} tiles)"
                            ),
                            "t0": tw,
                            "args": {"tiles": bd_tiles},
                        })
                    elif ev == "tune_probe":
                        t = _tune_scope(cur)
                        ok, probes = rec["ok"], rec["probes"]
                        t["groups_probed"] += 1
                        if not ok:
                            t["groups_skipped"] += 1
                        t["probes"] += probes
                        sp = rec.get("speedup")
                        if isinstance(sp, (int, float)) and not isinstance(
                            sp, bool
                        ):
                            t["best_speedup"] = (
                                sp if t["best_speedup"] is None
                                else max(t["best_speedup"], sp)
                            )
                    elif ev == "tune_profile":
                        t = _tune_scope(cur)
                        # last wins per scope (the terminal verdict)
                        t["profile"] = {
                            "key": rec["key"],
                            "source": rec["source"],
                            "probes": rec["probes"],
                            **(
                                {"age_s": rec["age_s"]}
                                if "age_s" in rec else {}
                            ),
                        }
                    elif ev == "program_cache":
                        # warm-cache verdict: one per job run scope (and a
                        # server-scope aggregate); last wins per scope
                        cur["program_cache"] = {
                            "hits": rec["hits"],
                            "misses": rec["misses"],
                            "compile_s": rec["compile_s"],
                            **({"keys": rec["keys"]} if "keys" in rec else {}),
                        }
                    elif ev == "run_done":
                        host_info.update(
                            status=rec.get("status"), wall_s=rec.get("wall_s"),
                            px_per_s=rec.get("px_per_s"),
                        )
                        for k, v in (rec.get("stage_s") or {}).items():
                            cur["stage_s"][k] = cur["stage_s"].get(k, 0.0) + v
                except (KeyError, TypeError):
                    # a field-incomplete record (torn write, foreign schema)
                    # must not kill a post-mortem fold
                    malformed += 1
                else:
                    cur["counts"][ev] = cur["counts"].get(ev, 0) + 1
        if errs is not None and not any_line:
            errs.append("file contains no events")
        hosts.append(host_info)
        folded.append(cur)

    # cross-file merge of each file's last scope
    counts: dict[str, int] = {}
    stage_s: dict[str, float] = {}
    for c in folded:
        for k, v in c["counts"].items():
            counts[k] = counts.get(k, 0) + v
        for k, v in c["stage_s"].items():
            stage_s[k] = stage_s.get(k, 0.0) + v

    # per-host rollup (the pod-imbalance view the run-level merge above
    # cannot show): each file's LAST scope gets its own stage shares —
    # the pre-existing fold summed stage_s across hosts, so a pod where
    # one host's write stage dominates read as a pod-wide write problem
    # — plus the span-derived idle gap, tail ratio and straggler count.
    per_host = []
    for i, c in enumerate(folded):
        h = hosts[i]
        total = sum(c["stage_s"].values())
        entry: dict = {
            "host": h.get("host"),
            "process_index": h.get("process_index"),
            "run_id": h.get("run_id"),
            "status": h.get("status"),
            "wall_s": h.get("wall_s"),
            "px_per_s": h.get("px_per_s"),
            "pixels": c["pixels"],
            "tiles_done": len(c["compute_s"]),
            "retries": c["retries"],
            "stragglers": c["stragglers"],
            "tiles_leased": c["tiles_leased"],
            "tiles_stolen": c["tiles_stolen"],
            "tiles_speculated": c["tiles_speculated"],
            "stage_s": {
                k: round(v, 4) for k, v in sorted(c["stage_s"].items())
            },
            "stage_share": {
                k: round(v / total, 4)
                for k, v in sorted(c["stage_s"].items())
            } if total else {},
            "span_s": {
                k: round(v, 4) for k, v in sorted(c["span_s"].items())
            },
            "tail_ratio": tail_ratio(c["compute_s"]),
        }
        busy = busy_union_s(c["intervals"])
        entry["busy_s"] = round(busy, 4)
        if isinstance(h.get("wall_s"), (int, float)):
            entry["idle_gap_s"] = round(max(h["wall_s"] - busy, 0.0), 4)
        per_host.append(entry)

    report = {
        "files": len(paths),
        "event_counts": counts,
        "pixels": sum(c["pixels"] for c in folded),
        "malformed": malformed,
        "tile_compute_s": _stats([v for c in folded for v in c["compute_s"]]),
        "tile_px_per_s": _stats([v for c in folded for v in c["px_per_s"]]),
        "tile_record_s": _stats([v for c in folded for v in c["record_s"]]),
        "retries": sum(c["retries"] for c in folded),
        "failures": sum(c["failures"] for c in folded),
        "quarantined": sum(c["quarantined"] for c in folded),
        "faults_injected": sum(c["faults_injected"] for c in folded),
        "stalls": sum(c["stalls"] for c in folded),
        "stragglers": sum(c["stragglers"] for c in folded),
        "tiles_leased": sum(c["tiles_leased"] for c in folded),
        "tiles_stolen": sum(c["tiles_stolen"] for c in folded),
        "tiles_speculated": sum(c["tiles_speculated"] for c in folded),
        "max_feed_backlog": max((c["max_feed_backlog"] for c in folded), default=0),
        "max_write_backlog": max((c["max_write_backlog"] for c in folded), default=0),
        "stage_s": {k: round(v, 4) for k, v in sorted(stage_s.items())},
        "feed_cache": _merge_feed_cache(folded),
        "fetch": _merge_xfer(folded, "fetch"),
        "upload": _merge_xfer(folded, "upload"),
        "ingest_store": _merge_ingest_store(folded),
        "serve": _merge_serve(folded),
        "router": _merge_router(folded),
        "request": _merge_request(folded),
        "batching": _merge_batching(folded),
        "program_cache": _merge_program_cache(folded),
        "tune": _merge_tune(folded),
        "slo": _merge_slo(folded),
        "resources": _merge_resources(folded),
        "hosts": hosts,
        "per_host": per_host,
    }
    return report, spans


def export_trace(spans: list[dict], hosts: list[dict], out_path: str) -> int:
    """Write the chrome://tracing JSON; returns the number of trace events."""
    if spans:
        t_base = min(s["t0"] for s in spans)
    else:
        t_base = 0.0
    events: list[dict] = []
    tids: dict[tuple[int, str], int] = {}

    def tid_of(fileno: int, name: str) -> int:
        key = (fileno, name)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == fileno]) + 1
            events.append({
                "ph": "M", "pid": fileno, "tid": tids[key],
                "name": "thread_name", "args": {"name": name},
            })
        return tids[key]

    # spans are keyed by file ordinal, so the process_name metadata must
    # be too — hosts[] is built in file order, and a file's recorded
    # process_index (shown in the label) need not match its ordinal
    for fileno, h in enumerate(hosts):
        label = f"proc {h.get('process_index', fileno)}"
        if h.get("host"):
            label += f" @ {h['host']}"
        events.append({
            "ph": "M", "pid": fileno, "tid": 0,
            "name": "process_name", "args": {"name": label},
        })
    for s in spans:
        ts = (s["t0"] - t_base) * _US
        if s["kind"] == "slice":
            events.append({
                "ph": "X", "pid": s["file"], "tid": tid_of(s["file"], s["tid"]),
                "name": s["name"], "cat": s["tid"], "ts": ts,
                "dur": max(s["dur"], 0.0) * _US, "args": s.get("args", {}),
            })
        elif s["kind"] == "instant":
            events.append({
                "ph": "i", "pid": s["file"], "tid": tid_of(s["file"], s["tid"]),
                "name": s["name"], "cat": "retry", "ts": ts, "s": "t",
                "args": s.get("args", {}),
            })
        elif s["kind"] == "counter":
            events.append({
                "ph": "C", "pid": s["file"], "tid": 0, "name": s["name"],
                "ts": ts, "args": s.get("args", {}),
            })
    from tools._measure import write_json_atomic

    write_json_atomic(
        out_path,
        {"traceEvents": events, "displayTimeUnit": "ms"},
        indent=None,
        trailing_newline=False,
    )
    return len(events)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="events.jsonl files, or workdirs containing them")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="also export a chrome://tracing / Perfetto trace")
    ap.add_argument("--no-validate", action="store_true",
                    help="skip the schema lint pass (malformed streams "
                    "still fold best-effort)")
    args = ap.parse_args(argv)

    try:
        paths = expand_event_paths(args.paths)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # lint and fold in ONE pass per file (fold collects the schema errors
    # while aggregating); a failed lint still refuses to report
    schema_errors: "dict[str, list[str]] | None" = (
        None if args.no_validate else {}
    )
    report, spans = fold(paths, schema_errors=schema_errors)
    if schema_errors is not None:
        bad = {p: e for p, e in schema_errors.items() if e}
        if bad:
            for p, errs in bad.items():
                for e in errs[:10]:
                    print(f"{p}: {e}", file=sys.stderr)
            print("error: schema validation failed (use --no-validate to "
                  "fold anyway)", file=sys.stderr)
            return 1
    if args.trace:
        report["trace"] = {
            "path": args.trace,
            "events": export_trace(spans, report["hosts"], args.trace),
        }
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
