#!/bin/bash
# Sequential TPU availability-window worker — round 4+.
#
# Replaces the bench_watch.sh + tpu_followup.sh PAIR for in-session use:
# both gate independently on a probe, so inside one window they run
# CONCURRENTLY and contend on the tunneled chip — round-4 observation
# (BENCH_r04_attempts.log 01:00-01:10 UTC): a second client's device_put
# during an active bench crashes the remote worker ("TPU worker process
# crashed or restarted") for EVERY batch size until it recovers.  One
# process, one queue, strictly one chip client at a time.
#
# Work queue (each step skipped once its artifact exists, so the script
# resumes across restarts; each success commits immediately — a window
# can close at any moment):
#   1. paired-K chain bench at 65536 px   -> BENCH_r${R}.json (paired-K)
#   2. TPU-platform f32-vs-f64 parity     -> PARITY_f32_tpu.json
#   3. TPU stage profile                  -> PROFILE_tpu_r${R}.json
#   4. 1M-px chunked bench upgrade        -> BENCH_r${R}.json (px=1048576)
#
# Usage: LT_ROUND=04 nohup bash tools/window_runner.sh & disown
cd /root/repo
R="${LT_ROUND:-04}"
LOG=/root/repo/BENCH_r${R}_attempts.log
BENCH=/root/repo/BENCH_r${R}.json

log() { echo "[$(date -u +%FT%TZ)] window_runner: $*" >> "$LOG"; }

probe_green() {
  timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1
}

# step predicates ---------------------------------------------------------
have_paired_bench() {
  python - "$BENCH" <<'EOF' 2>/dev/null
import json, sys
r = json.load(open(sys.argv[1]))
ok = (r.get("device_platform") not in (None, "cpu")
      and r.get("value", 0) > 0
      and "median_delta_s" in r)
sys.exit(0 if ok else 1)
EOF
}

have_1m_bench() {
  python - "$BENCH" <<'EOF' 2>/dev/null
import json, sys
r = json.load(open(sys.argv[1]))
ok = (r.get("device_platform") not in (None, "cpu")
      and r.get("value", 0) > 0
      and "median_delta_s" in r
      and r.get("px", 0) >= 1048576)
sys.exit(0 if ok else 1)
EOF
}

accept_bench() {  # $1 = candidate json line, $2 = min px; 0 if real-TPU
  printf '%s\n' "$1" | MIN_PX="$2" python -c '
import json, os, sys
try:
    r = json.loads(sys.stdin.readline() or "{}")
except ValueError:
    sys.exit(1)
ok = (r.get("device_platform") not in (None, "cpu")
      and r.get("value", 0) > 0
      and r.get("px", 0) >= int(os.environ["MIN_PX"]))
sys.exit(0 if ok else 1)' 2>/dev/null
}

commit_artifact() {  # $1 = path, $2 = message
  git -C /root/repo add "$1" >> "$LOG" 2>&1 && \
    git -C /root/repo commit -m "$2" -- "$1" >> "$LOG" 2>&1
}

for i in $(seq 1 500); do
  if ! probe_green; then
    log "probe $i: backend not up"
    sleep 300
    continue
  fi
  log "probe $i green — working the queue"

  if ! have_paired_bench; then
    out=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1500 LT_BENCH_PX=65536 \
          LT_BENCH_REPS=4 LT_BENCH_CHAIN_K=32 python bench.py 2>>"$LOG")
    log "bench-65k: $out"
    if accept_bench "$out" 1; then
      echo "$out" > "$BENCH"
      commit_artifact "$BENCH" "TPU bench artifact: paired-K 65536-px number (window runner)"
      log "BENCH committed (65536, paired-K)"
    else
      sleep 60   # let a crashed worker recover before the next queue pass
      continue
    fi
  fi

  if [ ! -f PARITY_f32_tpu.json ]; then
    if timeout 2400 python tools/parity_f32.py 65536 PARITY_f32_tpu.json \
         --f64-on-cpu >> "$LOG" 2>&1 \
       && python -c "import json; r=json.load(open('PARITY_f32_tpu.json')); exit(0 if r.get('platform') != 'cpu' else 1)" 2>/dev/null; then
      commit_artifact PARITY_f32_tpu.json "TPU-platform f32 parity artifact (window runner)"
      log "PARITY_f32_tpu committed"
    else
      rm -f PARITY_f32_tpu.json
      log "parity attempt failed; re-queueing"
      sleep 60
      continue
    fi
  fi

  if [ ! -f "PROFILE_tpu_r${R}.json" ]; then
    if timeout 2400 python tools/profile_stages.py 65536 "PROFILE_tpu_r${R}.json" \
         --platform=axon,cpu >> "$LOG" 2>&1 \
       && python -c "import json; exit(0 if json.load(open('PROFILE_tpu_r${R}.json')).get('platform') != 'cpu' else 1)" 2>/dev/null; then
      commit_artifact "PROFILE_tpu_r${R}.json" "TPU stage profile artifact (window runner)"
      log "PROFILE_tpu committed"
    else
      rm -f "PROFILE_tpu_r${R}.json"
      log "profile attempt failed; re-queueing"
      sleep 60
      continue
    fi
  fi

  if ! have_1m_bench; then
    out=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1500 \
          LT_BENCH_REPS=4 LT_BENCH_CHAIN_K=32 python bench.py 2>>"$LOG")
    log "bench-1M: $out"
    if accept_bench "$out" 1048576; then
      echo "$out" > "$BENCH"
      commit_artifact "$BENCH" "TPU bench artifact upgraded: paired-K 1M-px chunked number (window runner)"
      log "BENCH upgraded (1M, paired-K)"
    else
      sleep 60
      continue
    fi
  fi

  log "queue complete — all TPU artifacts present"
  exit 0
done
exit 1
