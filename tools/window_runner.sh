#!/bin/bash
# Sequential TPU availability-window worker — round 4+.
#
# Replaces the bench_watch.sh + tpu_followup.sh PAIR for in-session use:
# both gate independently on a probe, so inside one window they run
# CONCURRENTLY and contend on the tunneled chip — round-4 observation
# (BENCH_r04_attempts.log 01:00-01:10 UTC): a second client's device_put
# during an active bench crashes the remote worker ("TPU worker process
# crashed or restarted") for EVERY batch size until it recovers.  One
# process, one queue, strictly one chip client at a time.
#
# Each step is skipped once its artifact exists, so the script resumes
# across restarts; each success commits immediately — a window can close
# at any moment.
#
# Round-5 queue (artifact-gated, resumes across restarts):
#   1. paired-K 1M-px bench          -> BENCH_r${R}_build.json
#   2. packed-fetch 25M-px scene     -> SCENE_TPU_r05.json
#   3. on-chip impl identity (1M px) -> IMPL_IDENTITY_r05.json
#   4. fused-kernel TPU parity 1M px -> PARITY_f32_tpu_pallas_r05.json
# (BENCH_r${R}.json itself is driver-captured at round end; the build
# artifact is the session's fallback evidence.)
#
# Usage: LT_ROUND=05 nohup bash tools/window_runner.sh & disown
cd /root/repo
R="${LT_ROUND:-05}"
LOG=/root/repo/BENCH_r${R}_attempts.log
BENCH=/root/repo/BENCH_r${R}_build.json

log() { echo "[$(date -u +%FT%TZ)] window_runner: $*" >> "$LOG"; }

probe_green() {
  timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1
}

# step predicates ---------------------------------------------------------
have_1m_bench() {
  python - "$BENCH" <<'EOF' 2>/dev/null
import json, sys
r = json.load(open(sys.argv[1]))
ok = (r.get("device_platform") not in (None, "cpu")
      and r.get("value", 0) > 0
      and "median_delta_s" in r
      and r.get("px", 0) >= 1048576)
sys.exit(0 if ok else 1)
EOF
}

accept_bench() {  # $1 = candidate json line, $2 = min px; 0 if real-TPU
  printf '%s\n' "$1" | MIN_PX="$2" python -c '
import json, os, sys
try:
    r = json.loads(sys.stdin.readline() or "{}")
except ValueError:
    sys.exit(1)
ok = (r.get("device_platform") not in (None, "cpu")
      and r.get("value", 0) > 0
      and r.get("px", 0) >= int(os.environ["MIN_PX"]))
sys.exit(0 if ok else 1)' 2>/dev/null
}

commit_artifact() {  # $1 = path, $2 = message
  git -C /root/repo add "$1" >> "$LOG" 2>&1 && \
    git -C /root/repo commit -m "$2" -- "$1" >> "$LOG" 2>&1
}

for i in $(seq 1 500); do
  if ! probe_green; then
    log "probe $i: backend not up"
    sleep 300
    continue
  fi
  log "probe $i green — working the queue"

  if [ ! -f SCENE_TPU_r05.json ]; then
    if timeout 3500 python tools/scene_tpu_packed.py --size 5000 \
         --out SCENE_TPU_r05.json >> "$LOG" 2>&1 \
       && python -c "import json; exit(0 if json.load(open('SCENE_TPU_r05.json')).get('platform') == 'tpu' else 1)" 2>/dev/null; then
      commit_artifact SCENE_TPU_r05.json "Packed-fetch TPU scene artifact (window runner)"
      log "SCENE_TPU_r05 committed"
    else
      rm -f SCENE_TPU_r05.json
      log "packed scene attempt failed; re-queueing"
      sleep 60
      continue
    fi
  fi

  if [ ! -f IMPL_IDENTITY_r05.json ]; then
    if timeout 2400 python tools/impl_identity.py --out IMPL_IDENTITY_r05.json \
         >> "$LOG" 2>&1; then
      commit_artifact IMPL_IDENTITY_r05.json "On-chip impl identity artifact (window runner)"
      log "IMPL_IDENTITY_r05 committed"
    else
      rm -f IMPL_IDENTITY_r05.json
      log "identity attempt failed; re-queueing"
      sleep 60
      continue
    fi
  fi

  if [ ! -f PARITY_f32_tpu_pallas_r05.json ]; then
    if timeout 3500 python tools/parity_f32.py 1048576 PARITY_f32_tpu_pallas_r05.json \
         --platform=axon,cpu --f64-on-cpu --impl=pallas >> "$LOG" 2>&1 \
       && python -c "import json; r=json.load(open('PARITY_f32_tpu_pallas_r05.json')); exit(0 if 'tpu' in r.get('platform','') else 1)" 2>/dev/null; then
      commit_artifact PARITY_f32_tpu_pallas_r05.json "Fused-kernel TPU parity artifact (window runner)"
      log "PARITY_r05 committed"
    else
      rm -f PARITY_f32_tpu_pallas_r05.json
      log "parity attempt failed; re-queueing"
      sleep 60
      continue
    fi
  fi

  if ! have_1m_bench; then
    out=$(LT_BENCH_ATTEMPTS=1 LT_BENCH_TIMEOUT=1500 \
          LT_BENCH_REPS=4 LT_BENCH_CHAIN_K=32 python bench.py 2>>"$LOG")
    log "bench-1M: $out"
    if accept_bench "$out" 1048576; then
      echo "$out" > "$BENCH"
      commit_artifact "$BENCH" "TPU bench artifact upgraded: paired-K 1M-px chunked number (window runner)"
      log "BENCH upgraded (1M, paired-K)"
    else
      sleep 60
      continue
    fi
  fi

  log "queue complete — all TPU artifacts present"
  exit 0
done
exit 1
