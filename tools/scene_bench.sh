#!/bin/bash
# Config #3 scene-scale end-to-end benchmark (VERDICT r2 item #2):
# synthetic full-WRS-2-size stack -> run_stack -> assemble_outputs on CPU.
set -e
cd /root/repo
D=/root/repo/.scene_r03
LOG=$D/scene.log
mkdir -p "$D"
echo "[$(date -u +%FT%TZ)] synth start" >> "$LOG"
python -m land_trendr_tpu --platform cpu synth "$D/stack" --size 5000 \
  >> "$LOG" 2>&1
echo "[$(date -u +%FT%TZ)] segment start" >> "$LOG"
python tools/run_segment_measured.py --platform cpu segment "$D/stack" \
  --workdir "$D/work" --out-dir "$D/out" --tile-size 512 \
  > "$D/summary.json" 2> "$D/time.txt"
echo "[$(date -u +%FT%TZ)] segment done rc=$?" >> "$LOG"
