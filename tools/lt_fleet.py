"""`lt fleet` — the pod's single pane of glass over a telemetry dir.

Folds every per-process snapshot the fleet publishers
(:mod:`land_trendr_tpu.obs.publish`) wrote under a shared telemetry
directory into one pod view (:mod:`land_trendr_tpu.obs.aggregate`) and
renders the fleet report: per-host freshness (stale/corrupt/superseded
flagged, never silently dropped), the aggregated key metrics and SLO
counters, and every active alert the replicas' fleet loops are firing.

Modes:

* default — print one report and exit;
* ``--watch`` — refresh every ``--interval`` seconds until Ctrl-C;
* ``--json`` — the raw pod view as JSON (scripting; one-shot);
* ``--prom FILE`` — additionally write the aggregated Prometheus
  exposition (atomic tmp + rename; ``-`` prints it to stdout instead
  of the report) — the file a node_exporter textfile collector or any
  scraper ingests as THE pod's metrics;
* ``--serve-port N`` — serve the live aggregated exposition on
  ``GET /metrics`` and the pod view on ``GET /fleet`` (loopback by
  default), refreshed per request — N per-process snapshot files
  become one scrape target.

Exit codes: 0 ok, 2 usage/empty-directory error.

Usage:
    python tools/lt_fleet.py lt_work/telemetry
    python tools/lt_fleet.py lt_serve/telemetry --prom pod.prom
    python tools/lt_fleet.py lt_serve/telemetry --serve-port 9800
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.obs import aggregate  # noqa: E402

_CLEAR = "\x1b[2J\x1b[H"


def _fmt_age(secs: float) -> str:
    if secs < 90:
        return f"{secs:.1f}s"
    if secs < 5400:
        return f"{secs / 60:.1f}m"
    return f"{secs / 3600:.1f}h"


def _metric(view: dict, name: str) -> "float | None":
    for inst in view.get("metrics", []):
        if inst["name"] == name and not inst.get("labels"):
            v = inst.get("value")
            return None if v is None else float(v)
    return None


def render(view: dict) -> str:
    """The fleet report (a plain string — the caller owns the
    terminal)."""
    counts = view["counts"]
    lines = [
        f"lt fleet — {counts['folded']} host(s) folded, "
        f"{counts['stale']} stale, {counts['corrupt']} corrupt, "
        f"{counts['excluded']} excluded "
        f"(of {counts['snapshots']} snapshot(s))"
    ]
    lines.append("")
    lines.append(
        f"{'HOST':<18} {'PID':>7} {'KIND':<6} {'AGE':>7} {'FLAGS':<10} "
        f"{'PHASE':<9} {'TILES':>11} {'QUEUE':>5} {'STRAG':>5}"
    )
    for h in view["hosts"]:
        flags = ",".join(
            f for f, on in (
                ("stale", h.get("stale") and not h.get("corrupt")),
                ("corrupt", h.get("corrupt")),
                ("old-gen", h.get("superseded")),
                ("excl", h.get("excluded") and not h.get("corrupt")
                 and not h.get("superseded")),
            ) if on
        ) or "ok"
        state = h.get("state") or {}
        p = state.get("progress") or {}
        tiles = (
            f"{p.get('tiles_done', '-')}/{p.get('tiles_total', '-')}"
            if "tiles_done" in p else "-"
        )
        lines.append(
            f"{str(h.get('host') or h['path']):<18} "
            f"{str(h.get('pid') or '-'):>7} {h.get('kind', '-'):<6} "
            f"{_fmt_age(h['age_s']):>7} {flags:<10} "
            f"{str(p.get('phase', '-')):<9} {tiles:>11} "
            f"{str(p.get('queue_depth', '-')):>5} "
            f"{str(state.get('stragglers', '-')):>5}"
        )
    # tuning profiles: which profile (key + age + source) each host's
    # "auto" knobs resolved through — rendered so a MIXED tuned/untuned
    # fleet is visible instead of silent (a host with no tune state in
    # its snapshot simply ran with explicit/default knobs)
    tuned = [
        (h, (h.get("state") or {}).get("tune")) for h in view["hosts"]
    ]
    if any(t for _, t in tuned):
        lines.append("")
        lines.append("tune profiles:")
        for h, t in tuned:
            if not t:
                continue
            age = t.get("age_s")
            lines.append(
                f"  {h.get('host', '?')}:{h.get('pid', '?')} "
                f"{t.get('key') or 'defaults'} src {t.get('source', '?')}"
                + (
                    f" age {_fmt_age(age)}"
                    if isinstance(age, (int, float)) else ""
                )
            )
    # router aggregate: a kind="route" snapshot carries the routing
    # plane's state block (tenant queues, replica table, scaler) — the
    # fleet router publishes it so this view needs no HTTP
    for h in view["hosts"]:
        router = ((h.get("state") or {}).get("router")) or {}
        if not router:
            continue
        lines.append("")
        lines.append(f"router @ {h.get('host', '?')}:{h.get('pid', '?')}")
        tenants = router.get("tenants") or {}
        for name in sorted(tenants):
            t = tenants[name]
            lines.append(
                f"  tenant {name:<12} queued {t.get('queued', 0):>4} "
                f"routed {t.get('routed', 0):>4} "
                f"weight {t.get('weight', 1):g}"
            )
        for r in router.get("replicas") or []:
            lines.append(
                f"  replica {r.get('replica', '?'):<6} "
                f"{r.get('state', '?'):<9} inflight "
                f"{r.get('inflight', 0)} warm {r.get('warm_keys', 0)} "
                f"({r.get('base', '?')})"
            )
        scaler = router.get("scaler")
        if scaler:
            lines.append(
                f"  scaler burn {scaler.get('burn')} bounds "
                f"[{scaler.get('min_replicas')}, "
                f"{scaler.get('max_replicas')}] firing "
                f"{scaler.get('firing') or '-'}"
            )
    lines.append("")
    agg = []
    for label, name in (
        ("tiles", "lt_tiles_done_total"),
        ("pixels", "lt_pixels_total"),
        ("px/s", "lt_px_per_s"),
        ("retries", "lt_tile_retries_total"),
        ("stragglers", "lt_stragglers_total"),
        ("quarantined", "lt_tiles_quarantined_total"),
    ):
        v = _metric(view, name)
        if v is not None:
            agg.append(f"{label} {v:,.0f}")
    if agg:
        lines.append("pod: " + "  ".join(agg))
    slo = []
    for label, name in (
        ("met", "lt_slo_met_total"),
        ("missed", "lt_slo_missed_total"),
        ("burn(max)", "lt_slo_burn_rate"),
        ("queue", "lt_serve_queue_depth"),
        ("running", "lt_serve_running"),
    ):
        v = _metric(view, name)
        if v is not None:
            slo.append(f"{label} {v:g}")
    if slo:
        lines.append("slo: " + "  ".join(slo))
    rt = []
    for label, name in (
        ("forwards", "lt_router_jobs_routed_total"),
        ("warm", "lt_router_warm_routed_total"),
        ("rerouted", "lt_router_rerouted_total"),
        ("throttled", "lt_router_throttled_total"),
        ("replicas-ready", "lt_router_replicas_ready"),
    ):
        v = _metric(view, name)
        if v is not None:
            rt.append(f"{label} {v:g}")
    if rt:
        lines.append("router: " + "  ".join(rt))
    for c in view.get("conflicts", []):
        lines.append(f"merge conflict: {c}")
    lines.append("")
    if view.get("alerts"):
        lines.append("ALERTS:")
        for a in view["alerts"]:
            since = a.get("since_t")
            age = (
                f" for {_fmt_age(max(0.0, view['generated_t'] - since))}"
                if isinstance(since, (int, float)) else ""
            )
            lines.append(
                f"  {a.get('state', 'firing').upper():<9} "
                f"{a.get('rule', '?')} on {a.get('host', '?')}"
                f" (value {a.get('value')}, threshold "
                f"{a.get('threshold')}){age}"
            )
    else:
        lines.append("alerts: none firing")
    return "\n".join(lines)


def write_prom(view: dict, path: str) -> None:
    """Aggregated exposition via atomic tmp + rename (a scraper's cat
    never sees a torn file — the PromFileExporter discipline)."""
    text = aggregate.render_prom(view)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def serve(directory: str, port: int, host: str, stale_after_s: "float | None") -> int:
    """Serve the live aggregated exposition (+ pod view JSON)."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 - stdlib API name
            path = self.path.split("?")[0].rstrip("/")
            view = aggregate.fold_dir(directory, stale_after_s=stale_after_s)
            if path == "/metrics":
                body = aggregate.render_prom(view).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path in ("", "/fleet"):
                body = json.dumps(view, indent=2, default=str).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a) -> None:  # quiet: no per-scrape stderr
            pass

    httpd = http.server.ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    print(
        json.dumps({"serving": True, "port": httpd.server_address[1],
                    "dir": directory}),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="the shared telemetry directory "
                    "(WORKDIR/telemetry) the fleet publishes into")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw pod view as JSON (one-shot)")
    ap.add_argument("--prom", default=None, metavar="FILE",
                    help="also write the aggregated Prometheus "
                    "exposition to FILE (atomic; '-' = stdout)")
    ap.add_argument("--watch", action="store_true",
                    help="refresh the report every --interval seconds")
    ap.add_argument("--interval", type=float, default=2.0, metavar="SEC")
    ap.add_argument("--stale-after-s", type=float, default=None,
                    metavar="SEC",
                    help="staleness bound override (default: 3x each "
                    "snapshot's own publish interval)")
    ap.add_argument("--newer-than-age", type=float, default=None,
                    metavar="SEC",
                    help="exclude snapshots older than SEC from the "
                    "value fold (dead leftovers in a reused dir); they "
                    "stay listed as excluded")
    ap.add_argument("--serve-port", type=int, default=None, metavar="PORT",
                    help="serve the live aggregated /metrics exposition "
                    "and /fleet JSON on PORT (0 = ephemeral)")
    ap.add_argument("--serve-host", default="127.0.0.1", metavar="HOST",
                    help="bind address for --serve-port (loopback by "
                    "default; the exposition is read-only)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.dir):
        print(f"error: {args.dir} is not a directory", file=sys.stderr)
        return 2

    def fold() -> dict:
        now = time.time()
        return aggregate.fold_dir(
            args.dir,
            now=now,
            stale_after_s=args.stale_after_s,
            newer_than=(
                now - args.newer_than_age
                if args.newer_than_age is not None else None
            ),
        )

    if args.serve_port is not None:
        return serve(
            args.dir, args.serve_port, args.serve_host, args.stale_after_s
        )

    view = fold()
    if not view["counts"]["snapshots"]:
        print(
            f"error: no *.snap.json under {args.dir} (is --publish on?)",
            file=sys.stderr,
        )
        return 2
    if args.prom:
        if args.prom == "-":
            print(aggregate.render_prom(view), end="")
            return 0
        write_prom(view, args.prom)
    if args.json:
        print(json.dumps(view, indent=2, default=str))
        return 0
    if not args.watch:
        print(render(view))
        return 0
    try:
        while True:
            sys.stdout.write(_CLEAR + render(fold()) + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
