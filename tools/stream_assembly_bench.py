"""Measure streaming raster assembly: bounded RSS + CONUS-scale capability.

VERDICT r3 next-round item #2, "done" criteria:
* the 25M-px scene (SCENE_r03.json) assembles with peak RSS well under
  1 GB (run-wide round-3 peak was 7.6 GB, with full product mosaics
  materialised in host RAM), and
* a synthetic 40k×40k (1.6e9 px — BASELINE configs[4] CONUS ARD mosaic
  class) assembles at all, which the old ``np.zeros((depth, h, w))``
  path could not.

Two modes:

``scene``   re-assemble the round-3 scene workdir (``.scene_r03/work``,
            100 real 512² tile artifacts) through the streaming
            assemble_outputs into a throwaway out dir.
``mosaic``  fabricate manifest-format tile artifacts for an H×W raster
            (default 40000², 3 products incl. a multi-band one), then
            stream-assemble them.  Fabrication uses O(tile) memory and
            deflate artifacts so the workdir stays modest; the f32
            product's worst-case encoded bound exceeds u32 addressing, so
            the auto layout picks BigTIFF — exercising the streamed
            BigTIFF path at real scale.

Peak RSS is ``ru_maxrss`` of THIS process (fabrication + assembly
included).  Writes/merges STREAMASM_r04.json.

Usage: python tools/stream_assembly_bench.py scene|mosaic [--size=N]
"""

from __future__ import annotations

import os
import shutil
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _measure import merge_json, rss_mb as _rss_mb  # noqa: E402

OUT_JSON = os.path.join(REPO, "STREAMASM_r04.json")


def _merge(key: str, rec: dict) -> None:
    merge_json(OUT_JSON, key, rec)


def _stub_stack(years: np.ndarray, h: int, w: int, geo):
    """A RasterStack stand-in with the run's years/shape/geo but NO pixel
    cubes: assembly reads tile artifacts, not the stack — the fingerprint
    only hashes years+shape+config, and the zero-strided qa broadcast
    satisfies the ``shape`` property without allocating (NY, H, W)."""
    from land_trendr_tpu.runtime.stack import RasterStack

    return RasterStack(
        years=np.asarray(years, np.int32),
        dn_bands={},
        qa=np.broadcast_to(np.uint16(0), (len(years), h, w)),
        geo=geo,
    )


def scene_mode() -> int:
    import re

    import jax

    jax.config.update("jax_platforms", "cpu")
    from land_trendr_tpu.io.geotiff import read_geotiff

    d = os.path.join(REPO, ".scene_r03")
    out_dir = os.path.join(d, "out_stream_r04")
    from land_trendr_tpu.runtime import RunConfig, assemble_outputs

    cfg = RunConfig(
        tile_size=512,
        workdir=os.path.join(d, "work"),
        out_dir=out_dir,
    )
    stack_dir = os.path.join(d, "stack")
    names = sorted(n for n in os.listdir(stack_dir) if n.endswith(".tif"))
    years = [int(re.search(r"(\d{4})", n).group(1)) for n in names]
    # one full read for the grid's geo; the array is dropped immediately
    arr, geo, _ = read_geotiff(os.path.join(stack_dir, names[0]))
    h, w = arr.shape[-2:]
    del arr
    stack = _stub_stack(np.array(years), h, w, geo)
    rss0 = _rss_mb()
    t0 = time.perf_counter()
    paths = assemble_outputs(stack, cfg)
    wall = time.perf_counter() - t0
    sizes = {k: os.path.getsize(p) for k, p in paths.items()}
    rec = {
        "pixels": 25_000_000,
        "products": len(paths),
        "wall_s": round(wall, 1),
        "peak_rss_mb": round(_rss_mb(), 1),
        "rss_before_assemble_mb": round(rss0, 1),
        "out_bytes_total": sum(sizes.values()),
        "note": (
            "re-assembly of the round-3 25M-px scene workdir through the "
            "streaming writers; round-3 run-wide peak RSS was 7.6 GB "
            "(SCENE_r03.json) with full mosaics in host RAM"
        ),
    }
    shutil.rmtree(out_dir, ignore_errors=True)
    _merge("scene_25Mpx", rec)
    return 0


def mosaic_mode(size: int) -> int:
    from land_trendr_tpu.io.geotiff import GeoMeta
    from land_trendr_tpu.runtime.driver import RunConfig, assemble_outputs, plan_tiles
    from land_trendr_tpu.runtime.manifest import TileManifest

    h = w = int(size)
    tile = 2048  # NOT a multiple of 256: exercises partial-block buffering
    work = os.path.join(REPO, ".streamasm_work")
    out_dir = os.path.join(REPO, ".streamasm_out")
    shutil.rmtree(work, ignore_errors=True)
    shutil.rmtree(out_dir, ignore_errors=True)

    years = np.arange(1984, 1990, dtype=np.int32)
    stack = _stub_stack(
        years,
        h,
        w,
        GeoMeta(pixel_scale=(30.0, 30.0, 0.0), tiepoint=(0, 0, 0, 5e5, 4e6, 0)),
    )
    cfg = RunConfig(tile_size=tile, workdir=work, out_dir=out_dir)
    tiles = plan_tiles(h, w, tile)
    manifest = TileManifest(work, cfg.fingerprint(stack))
    manifest.open(resume=False)

    t0 = time.perf_counter()
    rng = np.random.default_rng(4)
    for t in tiles:
        npx = t.h * t.w
        # smooth-ish fields: realistic deflate ratios without big RAM
        base = rng.normal(0.05, 0.01, size=(npx,)).astype(np.float32)
        arrays = {
            "rmse": base,
            "model_valid": (base > 0.05),
            "vertex_years": np.tile(
                np.array([1984, 1987, 1989, 0, 0, 0, 0], np.int16), (npx, 1)
            ),
        }
        manifest.record(
            t.tile_id,
            arrays,
            {"y0": t.y0, "x0": t.x0, "h": t.h, "w": t.w},
            compress="deflate",
        )
    fab_s = time.perf_counter() - t0

    rss_after_fab = _rss_mb()
    t0 = time.perf_counter()
    paths = assemble_outputs(stack, cfg)
    wall = time.perf_counter() - t0
    # capture the high-water mark NOW: everything after this line is
    # verification, and a full read_geotiff of the (7, H, W) product would
    # put ~22 GB on the measurement (the round-4 first run's mistake)
    peak_rss = _rss_mb()

    with open(paths["rmse"], "rb") as f:
        rmse_magic = f.read(4)
    assert rmse_magic[:2] == b"II", rmse_magic
    rmse_big = rmse_magic[2] == 43  # BigTIFF version word
    from land_trendr_tpu.io.geotiff import read_geotiff

    mv, _, mv_info = read_geotiff(paths["model_valid"])  # the small product
    assert mv.shape == (h, w), mv.shape
    sizes = {k: os.path.getsize(p) for k, p in paths.items()}
    rec = {
        "height": h,
        "width": w,
        "pixels": h * w,
        "products": sorted(paths),
        "tile_size": tile,
        "fabricate_s": round(fab_s, 1),
        "assemble_wall_s": round(wall, 1),
        "peak_rss_mb": round(peak_rss, 1),
        "rss_after_fabricate_mb": round(rss_after_fab, 1),
        "rmse_bigtiff": bool(rmse_big),
        "model_valid_bigtiff": bool(mv_info.big),
        "out_bytes": sizes,
        "note": (
            "fabricated manifest artifacts (deflate) streamed into product "
            "writers; peak_rss_mb is the process high-water through "
            "fabrication + assembly, captured before any verification "
            "read; the old assemble path would need "
            f"{7 * h * w * 2 / 1e9:.0f} GB for vertex_years alone"
        ),
    }
    shutil.rmtree(work, ignore_errors=True)
    shutil.rmtree(out_dir, ignore_errors=True)
    _merge(f"mosaic_{h}x{w}", rec)
    return 0


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "scene"
    size = 40000
    for a in sys.argv[2:]:
        if a.startswith("--size="):
            size = int(a.split("=", 1)[1])
    sys.exit(scene_mode() if mode == "scene" else mosaic_mode(size))
