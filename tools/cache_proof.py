"""Prove the persistent compile cache converts TPU windows into numbers.

VERDICT r3 next-round item #1, "done" criterion: *a committed demonstration
that a cold process reaches its first timed rep with a warm cache in
< 60 s*.  Round 3's one hardware window died at compile; with
``jax_compilation_cache_dir`` wired into every entry point
(``land_trendr_tpu/utils/compilation_cache.py``), compile work from any
process — even one that later faults — persists on disk, so a reopened
window only ever pays compile once.

Method (CPU, the only device this box can count on): run the bench child
twice against ONE fresh cache directory and parse bench.py's
"warm-up done at Ns" stderr marker — the moment the first *timed* rep can
start (backend init + compile + warm-up execution all included).

* run 1 (cold cache): populates the dir; pays full XLA compile.
* run 2 (cold process, warm cache): must reach the marker in < 60 s.

Writes CACHE_r04.json:
    {"cold_s": ..., "warm_s": ..., "speedup": ..., "threshold_s": 60,
     "ok": bool, "cache_entries": N, "platform": "cpu"}

Usage: python tools/cache_proof.py [out.json]
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARKER = re.compile(r"warm-up done at ([0-9.]+)s")


def run_bench_child(cache_dir: str) -> tuple[float, float]:
    """One cold-process bench run; returns (time_to_first_timed_rep, wall)."""
    env = dict(
        os.environ,
        LT_BENCH_CHILD="1",
        LT_BENCH_PLATFORM="cpu",
        LT_BENCH_PX="65536",
        LT_BENCH_REPS="1",
        LT_BENCH_MODE="loop",
        LT_COMPILE_CACHE=cache_dir,
    )
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=REPO,
    )
    wall = time.perf_counter() - t0
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        raise RuntimeError(f"bench child rc={proc.returncode}")
    m = MARKER.search(proc.stderr)
    if not m:
        raise RuntimeError("bench child never printed the warm-up marker")
    return float(m.group(1)), wall


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "CACHE_r04.json")
    cache_dir = tempfile.mkdtemp(prefix="lt_cache_proof_")
    try:
        cold_s, cold_wall = run_bench_child(cache_dir)
        n_entries = len(os.listdir(cache_dir))
        if n_entries == 0:
            raise RuntimeError(
                "cold run wrote no cache entries — persistent cache not active"
            )
        warm_s, warm_wall = run_bench_child(cache_dir)
        rec = {
            "cold_s": round(cold_s, 1),
            "warm_s": round(warm_s, 1),
            "speedup": round(cold_s / warm_s, 2) if warm_s else None,
            "threshold_s": 60,
            "ok": warm_s < 60.0,
            "cache_entries": n_entries,
            "platform": "cpu",
            "px": 65536,
            "note": (
                "time from process start to bench.py's first timed rep "
                "(init+compile+warm-up); run 2 is a cold process against "
                "run 1's on-disk jax_compilation_cache_dir"
            ),
        }
        from tools._measure import write_json_atomic

        write_json_atomic(out_path, rec, indent=1)
        print(json.dumps(rec))
        return 0 if rec["ok"] else 1
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
