#!/bin/bash
# Config #4 multi-index artifact (VERDICT r2 item #4): 1024^2 scene, NBR
# segmentation + NDVI/TCW FTV rasters, spot-validated against the oracle.
set -e
cd /root/repo
D=/root/repo/.mi_r03
LOG=$D/mi.log
mkdir -p "$D"
echo "[$(date -u +%FT%TZ)] synth start" >> "$LOG"
python -m land_trendr_tpu --platform cpu synth "$D/stack" --size 1024 >> "$LOG" 2>&1
echo "[$(date -u +%FT%TZ)] segment start" >> "$LOG"
python tools/run_segment_measured.py --platform cpu segment "$D/stack" \
  --ftv ndvi,tcw --tile-size 512 \
  --workdir "$D/work" --out-dir "$D/out" \
  > "$D/summary.json" 2> "$D/time.txt"
echo "[$(date -u +%FT%TZ)] validate start" >> "$LOG"
python tools/validate_ftv.py "$D/stack" "$D/out" --samples=64 \
  --out="$D/ftv_validation.json" >> "$LOG" 2>&1
echo "[$(date -u +%FT%TZ)] done" >> "$LOG"
