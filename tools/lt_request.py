"""Assemble ONE request's cross-layer trace: router → replica → tiles.

The request-level consumer of the trace context
(:mod:`land_trendr_tpu.obs.reqtrace`): give it a ``trace_id`` and the
event streams it crossed — a router workdir expands to its own stream
plus every spawned replica's and every pinned job workdir's — and it
emits

* a JSON **record** on stdout: the journey timeline (router queue wait
  → route decision → each forward HOP with its target replica → replica
  admission queue → compile → per-tile feed/upload/compute/fetch/write),
  the hop list (a re-routed request shows BOTH forwards under the one
  id), and the **blame decomposition** — a priority-sweep PARTITION of
  the router-observed latency whose components sum to it by
  construction (``blame_sum_s == latency_s``);
* with ``--trace OUT.json``, a **Chrome trace-event file** of the
  journey on one wall-aligned timeline (one trace process per stream,
  one thread per blame component — ``obs_report.export_trace``, the
  same writer ``lt_trace`` uses);
* with ``--list``, the ``request_done`` index (slowest first) instead —
  "which trace do I assemble": the bridge from a p99 histogram bucket's
  exemplar ring (``/metrics/exemplars``, ``/debug/requests``) to a
  concrete journey.

Exit codes: 0 ok, 1 trace not found in the given streams, 2 usage/IO.

Usage:
    python tools/lt_request.py TRACE_ID ROUTER_WORKDIR [PATHS...]
    python tools/lt_request.py --list ROUTER_WORKDIR
    python tools/lt_request.py --slowest ROUTER_WORKDIR --trace out.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import obs_report  # noqa: E402  (the shared Chrome-trace exporter)

from land_trendr_tpu.obs.reqtrace import (  # noqa: E402
    assemble_request,
    discover_request_files,
    list_requests,
)


def expand_paths(paths: "list[str]") -> "list[str]":
    """CLI arguments → event streams: files pass through, a directory
    expands to the fleet layout's streams (its own ``events*.jsonl``,
    ``replicas/*/``, ``jobs/*/work/``).  Raises ``FileNotFoundError``
    for a missing path or a stream-less directory."""
    out: "list[str]" = []
    for p in paths:
        if os.path.isdir(p):
            found = discover_request_files(p)
            if not found:
                raise FileNotFoundError(f"no events*.jsonl under {p}")
            out.extend(found)
        elif os.path.exists(p):
            out.append(p)
        else:
            raise FileNotFoundError(f"{p} does not exist")
    # dedupe, keep order (a workdir given twice must not double-fold)
    seen: set = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def trace_events(record: dict) -> "tuple[list[dict], list[dict]]":
    """An assembled request → the ``obs_report.export_trace`` source
    shape: one slice per timeline entry, keyed by source stream, with
    the blame component as the trace thread."""
    src: "list[dict]" = []
    files = sorted({e["file"] for e in record.get("timeline", [])})
    index = {f: i for i, f in enumerate(files)}
    for e in record.get("timeline", []):
        name = e["component"]
        if e.get("tile") is not None:
            name = f"{e['component']} tile {e['tile']}"
        elif e.get("replica") is not None:
            name = f"{e['component']} → {e['replica']}"
        src.append({
            "kind": "slice",
            "file": index[e["file"]],
            "tid": e["component"],
            "name": name,
            "t0": e["t0"],
            "dur": e["dur"],
            "args": {
                k: e[k]
                for k in ("replica", "attempt", "ok", "tile", "job_id")
                if e.get(k) is not None
            },
        })
    hosts = [{"process_index": f, "host": None} for f in files]
    return src, hosts


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_id", nargs="?", default=None,
                    help="the request correlation id to assemble (from "
                    "/debug/requests, /metrics/exemplars, lt top's TRACE "
                    "column, or a job status snapshot)")
    ap.add_argument("paths", nargs="+",
                    help="event streams: events*.jsonl files, or a "
                    "router/serve workdir (expands to its own stream + "
                    "replicas/*/ + jobs/*/work/)")
    ap.add_argument("--list", action="store_true",
                    help="list every request_done in the streams, "
                    "slowest first, instead of assembling one")
    ap.add_argument("--slowest", action="store_true",
                    help="assemble the slowest request_done found "
                    "(no trace_id needed — the p99 hunt's default)")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="also export the journey as a chrome://tracing "
                    "/ Perfetto trace")
    args = ap.parse_args(argv)

    if args.list or args.slowest:
        # no trace_id needed: the first positional (if any) is a path
        raw = [p for p in (args.trace_id, *args.paths) if p is not None]
        try:
            files = expand_paths(raw)
        except FileNotFoundError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        index = list_requests(files)
        if args.list:
            print(json.dumps({"requests": index}, indent=2))
            return 0
        if not index:
            print("error: no request_done in the given streams",
                  file=sys.stderr)
            return 1
        trace_id = index[0]["trace_id"]
    else:
        if args.trace_id is None:
            print("error: a TRACE_ID is required (or --list/--slowest)",
                  file=sys.stderr)
            return 2
        trace_id = args.trace_id
        try:
            files = expand_paths(args.paths)
        except FileNotFoundError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    record = assemble_request(files, trace_id)
    if not record["found"]:
        print(
            f"error: trace {trace_id!r} not found in {len(files)} "
            "stream(s)", file=sys.stderr,
        )
        return 1
    if args.trace:
        src, hosts = trace_events(record)
        record["trace"] = {
            "path": args.trace,
            "events": obs_report.export_trace(src, hosts, args.trace),
        }
    record["files_scanned"] = files
    print(json.dumps(record, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
