"""Measure float32 vertex parity against the float64 kernel at scale.

The north-star correctness metric is vertex-for-vertex parity
(BASELINE.json); the kernel's f64 mode is exact against the CPU oracle
(tests/test_parity.py), so this tool quantifies the *remaining* axis — how
often pure float32 execution (the TPU's fast path) flips a vertex decision
— over a large synthetic population (VERDICT round-1 weak item #3: "no
measured vertex agreement rate f32-vs-f64 at scale").

Writes PARITY_f32.json with the exact-agreement rate and a disagreement
taxonomy:

* ``valid_flip``  — model_valid differs (a p-value crossed the threshold);
* ``count_diff``  — both valid, different number of vertices;
* ``placement``   — same count, at least one vertex index differs;
* ``exact``       — identical vertex_indices + n_vertices + model_valid.

Usage: python tools/parity_f32.py [n_pixels] [out.json] [--platform=cpu]
                                  [--f64-on-cpu]
(default 1,048,576 pixels in 64K chunks.  --platform defaults to cpu — f32
rounding there is the same IEEE arithmetic the TPU's VPU applies outside
the MXU — but fusion-order effects ARE platform-specific, so the number
the north star cares about is --platform=tpu on real hardware; the
``platform`` field in the artifact records which one was measured.  The
f32 tolerance contract itself lives in ops/segment.py.)

``--f64-on-cpu`` (use with ``--platform=axon,cpu`` or the container
default): the f32 pass runs on the first accelerator device while the f64
reference pass runs on the host CPU backend — the configuration that
answers the real question (TPU-f32 vs exact f64) without paying for
XLA's f64 emulation on a chip with no native f64.

NOTE: otherwise the f64 side runs wherever the default device is; on TPU
that means f64 emulation, which is slow but correct — the tool warns and
proceeds.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _platform_arg import pop_platform_arg  # noqa: E402

jax.config.update("jax_platforms", pop_platform_arg())

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from land_trendr_tpu.utils.compilation_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def make_population(px: int, ny: int, seed: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Mixed-regime synthetic series (disturbance/recovery, steps, trends,
    spikes, noise) with realistic masking — float64 master copies.  The
    generator itself lives in tools/_population.py (shared with
    parity_paramspace.py); this tool uses its defaults, which are this
    function's historical literal values and RNG draw order."""
    from _population import make_population as shared

    return shared(np.random.default_rng(seed), px, ny)  # disturbance-positive convention


def main() -> int:
    sys.setrecursionlimit(100_000)  # pallas kernel traces deeply under x64
    split = "--f64-on-cpu" in sys.argv
    if split:
        sys.argv.remove("--f64-on-cpu")
    impl = "xla"
    for arg in list(sys.argv):
        if arg.startswith("--impl="):
            impl = arg.split("=", 1)[1]
            sys.argv.remove(arg)
    if impl not in ("xla", "pallas"):
        print(f"unknown --impl={impl}", file=sys.stderr)
        return 2
    px_total = int(sys.argv[1]) if len(sys.argv) > 1 else 1_048_576
    out_path = sys.argv[2] if len(sys.argv) > 2 else "PARITY_f32.json"
    ny = 40
    chunk = 65_536

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels

    if impl == "pallas":
        # f32 leg only — the f64 reference leg stays on the XLA kernel
        # (bit-exact vs the oracle); interpret mode when the chip is a CPU
        from land_trendr_tpu.ops.segment_pallas import (
            jax_segment_pixels_pallas,
        )

    acc_dev = jax.devices()[0]
    plat = acc_dev.platform
    if split:
        cpu_dev = jax.devices("cpu")[0]
        platform_label = f"f32:{plat}/f64:cpu"
        print(f"parity_f32: split devices — {platform_label}",
              file=sys.stderr, flush=True)
    else:
        cpu_dev = None
        platform_label = plat
        if plat == "tpu":
            print(
                "parity_f32: TPUs have no native f64 — the f64 reference "
                "pass runs under XLA's f64 emulation (slow but correct); "
                "expect a long runtime (or pass --f64-on-cpu)",
                file=sys.stderr,
                flush=True,
            )

    params = LTParams()
    counts = {"exact": 0, "valid_flip": 0, "count_diff": 0, "placement": 0}
    rmse_delta_max = 0.0
    fitted_delta_p99: list[float] = []
    t0 = time.time()

    done = 0
    seed = 0
    while done < px_total:
        n = min(chunk, px_total - done)
        years, vals, mask = make_population(n, ny, seed)
        seed += 1

        if split:
            # committed placement: jit runs each pass on its input's device
            out64 = jax_segment_pixels(
                jax.device_put(years, cpu_dev),
                jax.device_put(vals, cpu_dev),
                jax.device_put(mask, cpu_dev),
                params,
            )
            if impl == "pallas":
                # compiled Mosaic cannot trace under x64 (see
                # segment_pallas.family_stats_pallas) — drop to 32-bit
                # semantics around the f32 leg only
                with jax.enable_x64(False):
                    out32 = jax_segment_pixels_pallas(
                        jax.device_put(years.astype(np.float32), acc_dev),
                        jax.device_put(vals.astype(np.float32), acc_dev),
                        jax.device_put(mask, acc_dev),
                        params,
                        interpret=plat == "cpu",
                    )
            else:
                out32 = jax_segment_pixels(
                    jax.device_put(years, acc_dev),
                    jax.device_put(vals.astype(np.float32), acc_dev),
                    jax.device_put(mask, acc_dev),
                    params,
                )
        else:
            out64 = jax_segment_pixels(years, vals, mask, params)
            if impl == "pallas":
                with jax.enable_x64(False):
                    out32 = jax_segment_pixels_pallas(
                        years.astype(np.float32), vals.astype(np.float32),
                        mask, params, interpret=plat == "cpu",
                    )
            else:
                out32 = jax_segment_pixels(
                    years, vals.astype(np.float32), mask, params
                )

        vi64 = np.asarray(out64.vertex_indices)
        vi32 = np.asarray(out32.vertex_indices)
        mv64 = np.asarray(out64.model_valid)
        mv32 = np.asarray(out32.model_valid)
        nv64 = np.asarray(out64.n_vertices)
        nv32 = np.asarray(out32.n_vertices)

        flip = mv64 != mv32
        cdiff = ~flip & (nv64 != nv32)
        same_shape = ~flip & ~cdiff
        placement = same_shape & (vi64 != vi32).any(axis=1)
        exact = same_shape & ~placement

        counts["valid_flip"] += int(flip.sum())
        counts["count_diff"] += int(cdiff.sum())
        counts["placement"] += int(placement.sum())
        counts["exact"] += int(exact.sum())

        r64 = np.asarray(out64.rmse)
        r32 = np.asarray(out32.rmse)
        rmse_delta_max = max(rmse_delta_max, float(np.abs(r64 - r32).max()))
        f_delta = np.abs(np.asarray(out64.fitted) - np.asarray(out32.fitted))
        fitted_delta_p99.append(float(np.percentile(f_delta, 99)))

        done += n
        print(
            f"  {done}/{px_total} px  exact so far: "
            f"{counts['exact'] / done:.4%}  ({time.time() - t0:.0f}s)",
            file=sys.stderr,
            flush=True,
        )

    total = sum(counts.values())
    assert total == px_total
    record = {
        "n_pixels": px_total,
        "n_years": ny,
        "platform": platform_label,
        "impl": impl,
        "exact_vertex_agreement": counts["exact"] / total,
        "taxonomy": {
            k: {"count": v, "rate": v / total} for k, v in counts.items()
        },
        "rmse_abs_delta_max": rmse_delta_max,
        "fitted_abs_delta_p99_max": max(fitted_delta_p99),
        "elapsed_s": round(time.time() - t0, 1),
    }
    from tools._measure import write_json_atomic

    write_json_atomic(out_path, record)
    print(json.dumps(record, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
