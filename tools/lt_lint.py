"""lt-lint CLI: run the repo's AST invariant checks (CI seam).

Runs the five LT rules (``land_trendr_tpu/lintkit``) over the tree and
exits 1 on any finding that is neither ``# lt: noqa[rule]``-suppressed
inline nor recorded (with a reason) in ``LINT_BASELINE.json``.  Exit 0 =
clean, 2 = usage/configuration error (including a baseline entry with no
reason — an exception nobody wrote down is not an exception).

    python tools/lt_lint.py                 # whole tree
    python tools/lt_lint.py --changed       # files touched vs git HEAD
    python tools/lt_lint.py --json          # machine-readable report
    python tools/lt_lint.py land_trendr_tpu/io/blockcache.py

``--changed`` is the pre-commit invocation (README §Static analysis):
per-file rules run only on modified/untracked Python files; the
repo-level coupling rules (LT004/LT005) run whenever one of their
source files (driver/cli/README, telemetry/schema) changed.

Wired into tier-1 as ``tests/test_lint.py::test_repo_tree_is_clean``,
so producer drift fails the suite the same way schema drift in an
events stream does.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.lintkit import (  # noqa: E402
    ALL_CHECKERS,
    Baseline,
    BaselineError,
    RepoCtx,
    default_checkers,
    run_rules,
)

BASELINE_FILE = "LINT_BASELINE.json"


def changed_files(root: Path) -> "set[str] | None":
    """Repo-relative Python files modified/added/untracked vs git HEAD,
    or None when git is unavailable (caller falls back to a full run)."""
    try:
        # -uall: list files INSIDE untracked directories individually — the
        # default collapses a new package to one 'dir/' entry that would
        # never match per-file scoping (a new-subsystem PR's exact shape)
        out = subprocess.run(
            ["git", "status", "--porcelain", "-uall"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    files: set[str] = set()
    for line in out.stdout.splitlines():
        # porcelain v1: XY <path> (renames: "XY old -> new")
        path = line[3:].split(" -> ")[-1].strip().strip('"')
        if path:
            files.add(path)
    return files


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole repo)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files modified vs git HEAD (pre-commit "
                         "mode); repo-level rules run when their sources "
                         "changed")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file (default: <repo>/{BASELINE_FILE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (every finding counts)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_CHECKERS:
            print(f"{cls.rule_id}  {cls.title}")
        return 0

    files = None
    if args.paths:
        files = []
        for p in args.paths:
            path = (REPO / p) if not Path(p).is_absolute() else Path(p)
            try:
                if path.is_dir():
                    files.extend(
                        str(f.relative_to(REPO))
                        for f in sorted(path.rglob("*.py"))
                        if "__pycache__" not in f.parts
                    )
                elif path.exists():
                    files.append(str(path.relative_to(REPO)))
                else:
                    print(f"error: {p} does not exist", file=sys.stderr)
                    return 2
            except ValueError:
                print(
                    f"error: {p} is outside the repo ({REPO}) — lt-lint "
                    "paths are repo-relative", file=sys.stderr,
                )
                return 2

    repo = RepoCtx(str(REPO), files=files)

    only: "set[str] | None" = None
    if args.changed:
        only = changed_files(REPO)
        if only is None:
            print(
                "warning: git unavailable; --changed falling back to a "
                "full run", file=sys.stderr,
            )

    baseline = None
    if not args.no_baseline:
        bpath = Path(args.baseline) if args.baseline else REPO / BASELINE_FILE
        if bpath.exists():
            try:
                baseline = Baseline.load(str(bpath))
            except (BaselineError, json.JSONDecodeError, OSError) as e:
                print(f"error: {bpath}: {e}", file=sys.stderr)
                return 2

    try:
        report = run_rules(repo, default_checkers(), baseline, only_files=only)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.paths or only is not None:
        # partial runs trivially leave other files' baseline entries
        # unmatched — staleness is only meaningful over the full tree
        report["unused_baseline"] = []

    findings = report["findings"]
    if args.as_json:
        print(json.dumps(
            {
                "clean": not findings,
                "findings": [f.to_dict() for f in findings],
                "baselined": [
                    {**f.to_dict(), "reason": e["reason"]}
                    for f, e in report["baselined"]
                ],
                "noqa_suppressed": report["noqa_suppressed"],
                "unused_baseline": report["unused_baseline"],
                "files_checked": len(repo.py_files),
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr)
        for e in report["unused_baseline"]:
            print(
                f"warning: stale baseline entry ({e['rule']} {e['file']}): "
                f"{e['reason']}", file=sys.stderr,
            )
        n_base = len(report["baselined"])
        print(
            f"lt-lint: {len(findings)} finding(s), {n_base} baselined, "
            f"{report['noqa_suppressed']} noqa-suppressed over "
            f"{len(repo.py_files)} files"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
