"""lt-lint CLI: run the repo's AST invariant checks (CI seam).

Runs the twelve LT rules (``land_trendr_tpu/lintkit``) over the tree and
exits 1 on any finding that is neither ``# lt: noqa[rule]``-suppressed
inline nor recorded (with a reason) in ``LINT_BASELINE.json``.  Exit 0 =
clean, 2 = usage/configuration error (including a baseline entry with no
reason — an exception nobody wrote down is not an exception).

    python tools/lt_lint.py                 # whole tree
    python tools/lt_lint.py --changed       # files touched vs git HEAD
    python tools/lt_lint.py --json          # machine-readable report
    python tools/lt_lint.py --sarif out.sarif   # SARIF 2.1.0 artifact
    python tools/lt_lint.py --prune-baseline    # drop stale entries
    python tools/lt_lint.py land_trendr_tpu/io/blockcache.py

``--changed`` is the pre-commit invocation (README §Static analysis):
per-file rules run only on modified/untracked Python files; the
repo-level rules (LT004/LT005 coupling, the LT006–LT009/LT011
interprocedural and registry-driven family) run whenever one of their
source files changed.  ``--sarif`` writes a
SARIF 2.1.0 log alongside whatever else was requested (``-`` =
stdout) — active findings as ``error`` results, baselined ones as
suppressed results carrying their written justification — so CI can
annotate PRs without parsing our JSON.  ``--prune-baseline`` rewrites
``LINT_BASELINE.json`` without the entries a FULL run no longer
matches (partial runs refuse: staleness is only meaningful over the
whole tree).

Wired into tier-1 as ``tests/test_lint.py::test_repo_tree_is_clean``,
so producer drift fails the suite the same way schema drift in an
events stream does.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.lintkit import (  # noqa: E402
    ALL_CHECKERS,
    Baseline,
    BaselineError,
    RepoCtx,
    default_checkers,
    run_rules,
)

BASELINE_FILE = "LINT_BASELINE.json"

#: wall-time bound on a full twelve-rule run, shared by the tier-1 gate
#: (tests/test_lint.py) and the perf-gate lint leg — a full run measures
#: ~12s in this container; the bound leaves slack for load, not for an
#: accidentally quadratic rule
LINT_BUDGET_S = 30.0


def sarif_report(report: dict, files_checked: int) -> dict:
    """SARIF 2.1.0 log for one run: active findings as ``error``
    results, baselined ones as suppressed results (kind ``external``,
    justification = the baseline reason).  Minimal but valid — CI
    annotators need ruleId/message/location and nothing else."""
    from land_trendr_tpu.lintkit import ALL_CHECKERS

    results = []
    for f in report["findings"]:
        results.append(
            {
                "ruleId": f.rule_id,
                "level": "error",
                "message": {"text": f.message},
                "locations": [_sarif_location(f)],
            }
        )
    for f, entry in report["baselined"]:
        results.append(
            {
                "ruleId": f.rule_id,
                "level": "note",
                "message": {"text": f.message},
                "locations": [_sarif_location(f)],
                "suppressions": [
                    {
                        "kind": "external",
                        "justification": entry["reason"],
                    }
                ],
            }
        )
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "lt-lint",
                        # NOTE: informationUri is deliberately omitted —
                        # SARIF 2.1.0 §3.19.17 requires an ABSOLUTE URI
                        # and this repo has no canonical URL; the rule
                        # docs live in README.md §Static analysis
                        "rules": [
                            {
                                "id": cls.rule_id,
                                "shortDescription": {"text": cls.title},
                            }
                            for cls in ALL_CHECKERS
                        ],
                    }
                },
                "results": results,
                "properties": {
                    "filesChecked": files_checked,
                    "noqaSuppressed": report["noqa_suppressed"],
                },
            }
        ],
    }


def _sarif_location(f) -> dict:
    loc = {
        "physicalLocation": {
            "artifactLocation": {"uri": f.file},
            "region": {"startLine": max(1, f.line)},
        }
    }
    if f.symbol:
        loc["logicalLocations"] = [
            {"fullyQualifiedName": f.symbol, "kind": "function"}
        ]
    return loc


def prune_baseline(path: Path, unused: list) -> int:
    """Rewrite the baseline without ``unused`` entries; returns how many
    were dropped.  Preserves the header comment and key order."""
    with open(path) as f:
        data = json.load(f)
    drop = {json.dumps(e, sort_keys=True) for e in unused}
    kept = [
        e
        for e in data.get("entries", [])
        if json.dumps(e, sort_keys=True) not in drop
    ]
    n = len(data.get("entries", [])) - len(kept)
    if n:
        data["entries"] = kept
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    return n


def changed_files(root: Path) -> "set[str] | None":
    """Repo-relative Python files modified/added/untracked vs git HEAD,
    or None when git is unavailable (caller falls back to a full run)."""
    try:
        # -uall: list files INSIDE untracked directories individually — the
        # default collapses a new package to one 'dir/' entry that would
        # never match per-file scoping (a new-subsystem PR's exact shape)
        out = subprocess.run(
            ["git", "status", "--porcelain", "-uall"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    files: set[str] = set()
    for line in out.stdout.splitlines():
        # porcelain v1: XY <path> (renames: "XY old -> new")
        path = line[3:].split(" -> ")[-1].strip().strip('"')
        if path:
            files.add(path)
    return files


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole repo)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files modified vs git HEAD (pre-commit "
                         "mode); repo-level rules run when their sources "
                         "changed")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file (default: <repo>/{BASELINE_FILE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (every finding counts)")
    ap.add_argument("--sarif", default=None, metavar="FILE",
                    help="additionally write a SARIF 2.1.0 log to FILE "
                         "('-' = stdout); baselined findings ride along "
                         "as suppressed results with their reasons")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline without entries this FULL "
                         "run no longer matches (refused on partial runs)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_CHECKERS:
            print(f"{cls.rule_id}  {cls.title}")
        return 0

    if args.as_json and args.sarif == "-":
        # both reports on stdout would concatenate two JSON documents,
        # breaking every consumer of either
        print(
            "error: --json and --sarif - both claim stdout; write the "
            "SARIF to a file", file=sys.stderr,
        )
        return 2

    files = None
    if args.paths:
        files = []
        for p in args.paths:
            path = (REPO / p) if not Path(p).is_absolute() else Path(p)
            try:
                if path.is_dir():
                    files.extend(
                        str(f.relative_to(REPO))
                        for f in sorted(path.rglob("*.py"))
                        if "__pycache__" not in f.parts
                    )
                elif path.exists():
                    files.append(str(path.relative_to(REPO)))
                else:
                    print(f"error: {p} does not exist", file=sys.stderr)
                    return 2
            except ValueError:
                print(
                    f"error: {p} is outside the repo ({REPO}) — lt-lint "
                    "paths are repo-relative", file=sys.stderr,
                )
                return 2

    if args.sarif and args.sarif != "-":
        # probe the artifact path BEFORE the run: an unwritable --sarif
        # target is a CONFIG error (exit 2), and discovering it after a
        # ~12s twelve-rule pass wastes the whole run
        try:
            with open(args.sarif, "a"):
                pass
        except OSError as e:
            print(f"error: --sarif {args.sarif}: {e}", file=sys.stderr)
            return 2

    partial = bool(args.paths) or args.changed
    if args.prune_baseline:
        # refused up front — staleness is only meaningful over the full
        # tree, so there is no point paying for a partial run first
        if partial:
            print(
                "error: --prune-baseline needs a full run (no paths, no "
                "--changed) — a partial run cannot tell stale from "
                "unvisited", file=sys.stderr,
            )
            return 2
        if args.no_baseline:
            print(
                "error: --prune-baseline without a baseline in effect",
                file=sys.stderr,
            )
            return 2

    repo = RepoCtx(str(REPO))

    # positional paths scope the run exactly like --changed: per-file
    # rules parse and walk just the named files, while repo-level rules
    # (the registry-driven LT004/LT005/LT009/LT011 and the call-graph
    # family) still see the whole tree — a one-file run must not
    # misread PURE_MACHINES/SEAMS as drifted merely because the
    # machines were outside the file list
    only: "set[str] | None" = set(files) if files is not None else None
    if args.changed:
        changed = changed_files(REPO)
        if changed is None:
            print(
                "warning: git unavailable; --changed falling back to a "
                "full run", file=sys.stderr,
            )
        else:
            only = changed if only is None else (only & changed)

    baseline = None
    if not args.no_baseline:
        bpath = Path(args.baseline) if args.baseline else REPO / BASELINE_FILE
        if bpath.exists():
            try:
                baseline = Baseline.load(str(bpath))
            except (BaselineError, json.JSONDecodeError, OSError) as e:
                print(f"error: {bpath}: {e}", file=sys.stderr)
                return 2

    try:
        report = run_rules(repo, default_checkers(), baseline, only_files=only)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if only is not None:
        # partial runs trivially leave other files' baseline entries
        # unmatched — staleness is only meaningful over the full tree
        report["unused_baseline"] = []

    if args.prune_baseline:
        if baseline is None:
            print(
                "error: --prune-baseline without a baseline in effect",
                file=sys.stderr,
            )
            return 2
        bpath = Path(args.baseline) if args.baseline else REPO / BASELINE_FILE
        n = prune_baseline(bpath, report["unused_baseline"])
        print(
            f"lt-lint: pruned {n} stale baseline entr"
            f"{'y' if n == 1 else 'ies'} from {bpath.name}",
            file=sys.stderr,
        )
        report["unused_baseline"] = []

    # the per-file walk count: scoped runs report their scope, not the
    # tree the repo-level rules happened to consult
    n_checked = (
        len(repo.py_files) if only is None
        else len(only & set(repo.py_files))
    )

    if args.sarif:
        sarif = sarif_report(report, n_checked)
        if args.sarif == "-":
            print(json.dumps(sarif, indent=2))
        else:
            try:
                with open(args.sarif, "w") as f:
                    json.dump(sarif, f, indent=2)
                    f.write("\n")
            except OSError as e:
                # an unwritable artifact path is a CONFIG error (exit 2),
                # not "findings present" (exit 1)
                print(f"error: --sarif {args.sarif}: {e}", file=sys.stderr)
                return 2

    findings = report["findings"]
    if args.as_json:
        print(json.dumps(
            {
                "clean": not findings,
                "findings": [f.to_dict() for f in findings],
                "baselined": [
                    {**f.to_dict(), "reason": e["reason"]}
                    for f, e in report["baselined"]
                ],
                "noqa_suppressed": report["noqa_suppressed"],
                "unused_baseline": report["unused_baseline"],
                "files_checked": n_checked,
            },
            indent=2,
        ))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr)
        for e in report["unused_baseline"]:
            print(
                f"warning: stale baseline entry ({e['rule']} {e['file']}): "
                f"{e['reason']}", file=sys.stderr,
            )
        n_base = len(report["baselined"])
        print(
            f"lt-lint: {len(findings)} finding(s), {n_base} baselined, "
            f"{report['noqa_suppressed']} noqa-suppressed over "
            f"{n_checked} files",
            # SARIF-on-stdout owns stdout; the human summary moves aside
            file=sys.stderr if args.sarif == "-" else sys.stdout,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
