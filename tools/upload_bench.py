"""Upload-path benchmark: packed async host→device upload + ingest store.

The two halves of this PR's host-path work, measured in one artifact
(``--out``, e.g. ``UPLOAD_r10.json``):

**Upload** — builds a synthetic fed-tile workload (the band/QA arrays
``_feed_tile`` produces: uint16 DN bands + uint16 QA, ``(tile_px, NY)``)
and measures the dispatch-side transfer stage three ways over the same
tile sweep, all through the real :class:`runtime.feed.TileUploader`:

* ``per_array_sync`` — the pre-packing baseline: one synchronous
  ``jax.device_put`` per fed array per tile (the driver's
  ``--no-packed-upload`` fallback);
* ``packed_sync``   — ONE host-side pack + ONE transfer per tile,
  awaited immediately (isolates the transfer-count win);
* ``packed_async``  — the driver's production pipeline: tile *i*'s
  packed buffer crosses the link while tile *i-1* "computes", bounded at
  ``--depth`` in flight (adds the overlap win).

**Link model.** Same as ``tools/fetch_bench.py``: on this container's
CPU backend a host→device "transfer" is near zero-copy, so the
per-transfer cost that dominates real accelerator links is modeled at
the transfer points — each transfer lands ``latency + bytes/bandwidth``
after issue (``--link-ms`` / ``--link-gbps``, default PCIe-class 1 ms /
8 GB/s; both 0 disables for raw hardware measurement).  All host work —
the pack memcpy, ``device_put``, the jitted device unpack — is genuinely
executed, and ``raw_local`` records the unmodeled walls.  Parity (packed
unpack ≡ the original fed arrays, byte for byte) is asserted on real
arrays every run.

**Ingest store** — reuses ``tools/feed_bench.py``'s synthetic
tiled-deflate scene and window sweep to measure the persistent
decoded-block store (:mod:`land_trendr_tpu.io.blockstore`): store-off
baseline, cold ingest, warm rerun (same process), and a restart rerun
(fresh ``BlockStore`` over the same directory — the "second run over the
same stacks" case).  The warm/restart passes must show TIFF decode fully
skipped (store hit rate ≈ 100%, zero RAM-tier decodes) with
byte-identical window reads vs store-off.

``--smoke`` shrinks both workloads to seconds scale — the tier-1 mode
``tests/test_upload.py`` runs in CI.

Usage:
    python tools/upload_bench.py --out UPLOAD_r10.json
    python tools/upload_bench.py --smoke --out /tmp/upload_smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402

sys.path.insert(0, str(REPO / "tools"))
from _platform_arg import pop_platform_arg  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", pop_platform_arg())

from land_trendr_tpu.config import LTParams  # noqa: E402
from land_trendr_tpu.io import blockcache  # noqa: E402
from land_trendr_tpu.io.blockstore import BlockStore  # noqa: E402
from land_trendr_tpu.runtime import RunConfig  # noqa: E402
from land_trendr_tpu.runtime import feed as feedmod  # noqa: E402


def synth_inputs(px: int, ny: int, bands: int, seed: int):
    """One fed tile's arrays, the shapes/dtypes ``_feed_tile`` produces:
    uint16 DN bands + uint16 QA, ``(px, ny)``.  Random data is fine —
    the upload stage moves bytes, it never looks at them."""
    rng = np.random.default_rng(seed)
    names = [f"b{i}" for i in range(bands)]
    dn = {
        n: rng.integers(7273, 43636, (px, ny)).astype(np.uint16)
        for n in names
    }
    qa = rng.integers(0, 2, (px, ny)).astype(np.uint16) * 21824
    return dn, qa


class LinkModel:
    """Per-transfer cost model: a transfer issued now lands at
    ``now + latency_s + bytes/bw``; waiting sleeps out the remainder."""

    def __init__(self, latency_ms: float, gbps: float) -> None:
        self.latency_s = latency_ms / 1e3
        self.bps = gbps * 1e9

    @property
    def enabled(self) -> bool:
        return self.latency_s > 0 or self.bps > 0

    def land_at(self, nbytes: int) -> float:
        dt = self.latency_s + (nbytes / self.bps if self.bps else 0.0)
        return time.perf_counter() + dt

    def wait(self, land_at: float) -> None:
        while True:
            dt = land_at - time.perf_counter()
            if dt <= 0:
                return
            time.sleep(dt)


def run_per_array(cfg, payloads, n_tiles, link: LinkModel) -> dict:
    """The production fallback: one ``device_put`` per fed array per
    tile, each paying the modeled per-transfer link cost synchronously
    (the dispatch-stage shape of the pre-PR driver)."""
    up = feedmod.TileUploader(cfg, packed=False)
    t0 = time.perf_counter()
    for i in range(n_tiles):
        dn, qa = payloads[i % len(payloads)]
        handle = up.start(dn, qa)
        h_dn, h_qa = handle.arrays()
        # one device_put per array, each paying the link's per-transfer
        # cost before the next is issued — the synchronous per-array
        # dispatch shape (nothing host-blocks on the placed arrays; the
        # device consumes them, exactly like the real dispatch)
        for a in (*h_dn.values(), h_qa):
            jax.device_put(a)
            if link.enabled:
                link.wait(link.land_at(a.nbytes))
    wall = time.perf_counter() - t0
    return {"wall_s": wall, "stats": up.summary()}


def run_packed(cfg, payloads, n_tiles, link: LinkModel, depth: int) -> dict:
    """The driver's packed pipeline shape: pack + async device_put,
    bounded in-flight queue, device unpack on landed buffers.
    ``depth=1`` = fully sync."""
    up = feedmod.TileUploader(cfg, packed=True)
    queue: list[tuple[object, float]] = []

    def drain(limit: int) -> None:
        while len(queue) > limit:
            handle, land_at = queue.pop(0)
            if link.enabled:
                link.wait(land_at)
            # the driver's real resolution point: wait out the landing,
            # dispatch the device unpack; the tile program consumes the
            # unpacked arrays lazily (no host block on them)
            handle.arrays()

    t0 = time.perf_counter()
    for i in range(n_tiles):
        dn, qa = payloads[i % len(payloads)]
        handle = up.start(dn, qa)
        wire = feedmod.plan_wire_bytes(up.plan)
        queue.append((handle, link.land_at(wire)))
        drain(depth - 1)
    drain(0)
    wall = time.perf_counter() - t0
    return {"wall_s": wall, "stats": up.summary()}


def check_parity(cfg, payloads) -> int:
    """Packed device arrays must be byte-identical to the fed host
    arrays (real arrays, link model off)."""
    up = feedmod.TileUploader(cfg, packed=True)
    checked = 0
    for dn, qa in payloads:
        u_dn, u_qa = up.start(dn, qa).arrays()
        for name, host in (*dn.items(), ("qa", qa)):
            got = np.asarray(u_qa if name == "qa" else u_dn[name])
            if (
                got.dtype != host.dtype
                or got.shape != host.shape
                or got.tobytes() != host.tobytes()
            ):
                raise AssertionError(f"upload parity mismatch on {name}")
            checked += 1
    return checked


def bench_store(args, tmp_root: str) -> dict:
    """The ingest-store phase: store-off vs cold vs warm vs restart over
    the feed bench's scene/sweep, with byte-identity asserted."""
    import feed_bench

    scene_dir = os.path.join(tmp_root, "scene")
    store_dir = os.path.join(tmp_root, "store")
    paths = feed_bench.build_scene(
        scene_dir, args.store_size, args.store_years, args.seed
    )
    wins = feed_bench.plan_windows(args.store_size, args.store_window)
    px = args.store_size * args.store_size * args.store_years

    def timed_sweep() -> tuple[float, dict]:
        cache_base = blockcache.stats_snapshot()
        t0 = time.perf_counter()
        feed_bench.sweep(paths, wins, readahead=False)
        return time.perf_counter() - t0, blockcache.stats_delta(cache_base)

    # RAM tier OFF throughout: this phase isolates the persistent store
    # (the driver composes both; feed_bench measures the RAM tier)
    blockcache.configure(0, 1)
    timed_sweep()  # untimed warmup: page-cache the scene files
    off_wall, off_cache = timed_sweep()

    store = BlockStore(store_dir, budget_bytes=args.store_mb << 20)
    blockcache.configure(0, 1, store=store)
    base = store.stats_snapshot()
    cold_wall, cold_cache = timed_sweep()
    cold = store.stats_delta(base)
    store.flush()

    base = store.stats_snapshot()
    warm_wall, warm_cache = timed_sweep()
    warm = store.stats_delta(base)
    parity_warm = feed_bench.check_parity(paths, wins)
    store.close()

    # restart: a FRESH BlockStore over the same directory — the "second
    # run over the same stacks" service-mode case
    store2 = BlockStore(store_dir, budget_bytes=args.store_mb << 20)
    blockcache.configure(0, 1, store=store2)
    base = store2.stats_snapshot()
    restart_wall, restart_cache = timed_sweep()
    restart = store2.stats_delta(base)
    parity_restart = feed_bench.check_parity(paths, wins)
    store2.close()
    blockcache.configure(0, None)

    def rate(s: dict) -> float | None:
        lookups = s["hits"] + s["misses"]
        return round(s["hits"] / lookups, 4) if lookups else None

    for name, s in (("warm", warm), ("restart", restart)):
        if s["misses"]:
            raise AssertionError(
                f"{name} store pass missed {s['misses']} blocks — decode "
                "was not fully skipped"
            )
    return {
        "scene": {
            "size": args.store_size,
            "years": args.store_years,
            "window": args.store_window,
            "windows": len(wins),
            "pixels": px,
            "layout": "tiled-256 deflate+predictor uint16",
        },
        "store_mb": args.store_mb,
        "store_off": {"wall_s": round(off_wall, 4), "decode_s": off_cache["decode_s"]},
        "store_cold": {
            "wall_s": round(cold_wall, 4),
            "decode_s": cold_cache["decode_s"],
            "stats": cold,
            "hit_rate": rate(cold),
        },
        "store_warm": {
            "wall_s": round(warm_wall, 4),
            "decode_s": warm_cache["decode_s"],
            "stats": warm,
            "hit_rate": rate(warm),
        },
        "store_restart": {
            "wall_s": round(restart_wall, 4),
            "decode_s": restart_cache["decode_s"],
            "stats": restart,
            "hit_rate": rate(restart),
        },
        "speedup_warm": round(off_wall / warm_wall, 3) if warm_wall else None,
        "speedup_restart": (
            round(off_wall / restart_wall, 3) if restart_wall else None
        ),
        "parity_windows_checked": parity_warm + parity_restart,
        "parity_ok": True,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tile", type=int, default=128,
                    help="tile edge in px (tile_px = tile^2)")
    ap.add_argument("--years", type=int, default=24)
    ap.add_argument("--bands", type=int, default=2,
                    help="DN bands per tile (NBR needs 2; QA always rides)")
    ap.add_argument("--tiles", type=int, default=16)
    ap.add_argument("--depth", type=int, default=2,
                    help="async in-flight bound (RunConfig.upload_depth)")
    ap.add_argument("--link-ms", type=float, default=5.0,
                    help="modeled per-transfer latency (0 = no model). "
                    "Default 5 ms: conservative for the RPC-dispatch "
                    "link class this stage is bound by in practice — "
                    "SCENE_TPU_r05 measured ~531 ms of dispatch per "
                    "3-transfer tile (~177 ms/transfer) through the "
                    "tunneled chip; fetch_bench's PCIe-class 1 ms also "
                    "shows the win, but on this 2-core container the "
                    "packed path's genuine host work (pack memcpy + "
                    "device_put copy — DMA'd on real accelerators) "
                    "would then mask the 3-transfers-to-1 reduction "
                    "the driver actually buys")
    ap.add_argument("--link-gbps", type=float, default=8.0,
                    help="modeled link bandwidth (0 = latency-only model)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per mode; MEDIAN wall reported")
    ap.add_argument("--seed", type=int, default=20260803)
    ap.add_argument("--store-size", type=int, default=2048,
                    help="ingest-store phase: scene edge (px)")
    ap.add_argument("--store-years", type=int, default=6)
    ap.add_argument("--store-window", type=int, default=192)
    ap.add_argument("--store-mb", type=int, default=256)
    ap.add_argument("--no-store", action="store_true",
                    help="skip the ingest-store phase")
    ap.add_argument("--out", default="UPLOAD_r10.json")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny workload, seconds not minutes (tier-1 CI)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.tile = min(args.tile, 64)
        args.years = min(args.years, 12)
        args.tiles = min(args.tiles, 4)
        args.reps = 1
        args.store_size = min(args.store_size, 512)
        args.store_years = min(args.store_years, 3)
        args.store_window = min(args.store_window, 160)

    px = args.tile * args.tile
    cfg = RunConfig(
        index="nbr", params=LTParams(), tile_size=args.tile,
        upload_packed=True, upload_depth=args.depth,
    )
    # two distinct payloads alternated across the sweep (content never
    # matters to the upload stage; two keep any caching honest)
    payloads = [
        synth_inputs(px, args.years, args.bands, args.seed + k)
        for k in (0, 1)
    ]
    link = LinkModel(args.link_ms, args.link_gbps)
    no_link = LinkModel(0.0, 0.0)

    # parity first (and the compile warmup for the unpack program)
    parity_arrays = check_parity(cfg, payloads)

    def median(mode_fn) -> dict:
        runs = [mode_fn() for _ in range(max(1, args.reps))]
        runs.sort(key=lambda r: r["wall_s"])
        return runs[len(runs) // 2]

    n = args.tiles
    per_array = median(lambda: run_per_array(cfg, payloads, n, link))
    packed_sync = median(lambda: run_packed(cfg, payloads, n, link, 1))
    packed_async = median(
        lambda: run_packed(cfg, payloads, n, link, args.depth)
    )
    raw_pa = median(lambda: run_per_array(cfg, payloads, n, no_link))
    raw_pk = median(lambda: run_packed(cfg, payloads, n, no_link, args.depth))

    wire = packed_sync["stats"]["bytes"] // max(
        1, packed_sync["stats"]["transfers"]
    )
    result = {
        "workload": {
            "tile_px": px,
            "years": args.years,
            "bands": args.bands,
            "tiles": n,
            "bytes_per_tile_packed": wire,
            "transfers_per_tile_per_array": args.bands + 1,
            "transfers_per_tile_packed": 1,
        },
        "platform": jax.default_backend(),
        "link_model": {
            "latency_ms": args.link_ms,
            "gbps": args.link_gbps,
            "note": (
                "transfers land latency + bytes/bandwidth after issue; "
                "models the per-transfer cost of a real accelerator link "
                "(absent on this CPU backend's near-zero-copy device_put) "
                "— all host work (pack/device_put/unpack) is real; "
                "raw_local records the unmodeled walls"
            ) if link.enabled else "disabled: raw hardware measurement",
        },
        "per_array_sync": {
            "wall_s": round(per_array["wall_s"], 4),
            "ms_per_tile": round(per_array["wall_s"] / n * 1e3, 3),
        },
        "packed_sync": {
            "wall_s": round(packed_sync["wall_s"], 4),
            "ms_per_tile": round(packed_sync["wall_s"] / n * 1e3, 3),
        },
        "packed_async": {
            "wall_s": round(packed_async["wall_s"], 4),
            "ms_per_tile": round(packed_async["wall_s"] / n * 1e3, 3),
            "depth": args.depth,
            "note": (
                "depth>1 overlaps each tile's modeled link time with the "
                "NEXT tiles' pack work — the stand-in for the device "
                "compute the driver overlaps (it issues uploads as feeds "
                "complete, so a landing transfer crosses while the tile "
                "ahead computes)"
            ),
        },
        "speedup_packed_sync": round(
            per_array["wall_s"] / packed_sync["wall_s"], 3
        ),
        "speedup_packed_async": round(
            per_array["wall_s"] / packed_async["wall_s"], 3
        ),
        "raw_local": {
            "per_array_ms_per_tile": round(raw_pa["wall_s"] / n * 1e3, 3),
            "packed_ms_per_tile": round(raw_pk["wall_s"] / n * 1e3, 3),
            "note": "no link model; CPU-backend device_put is near zero-copy",
        },
        "parity": {
            "tiles_checked": len(payloads),
            "arrays_checked": parity_arrays,
            "ok": True,
        },
    }

    if not args.no_store:
        tmp = tempfile.mkdtemp(prefix="lt_upload_bench_")
        try:
            result["ingest_store"] = bench_store(args, tmp)
        finally:
            blockcache.configure(0, None)
            shutil.rmtree(tmp, ignore_errors=True)

    from tools._measure import write_json_atomic

    write_json_atomic(args.out, result)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
