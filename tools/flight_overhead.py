"""Flight-recorder overhead proof: ring+sampler on vs off on the smoke scene.

The ``OBS_OVERHEAD_r06`` methodology (telemetry on vs off, alternating
reps, median of the wall times) extended to the flight recorder: BOTH
sides run with telemetry on; the "on" side additionally mirrors every
emit into the flight ring and runs the resource sampler at an
aggressive period (far faster than the production default, so the
sampler actually fires many times inside a short smoke run).  The claim
under test is the tentpole's "lock-light" promise: mirroring an emit is
a deque append, sampling is a /proc read every interval — the run's
wall time must stay within the container's run-to-run noise band.

Structural checks ride along (the perf-gate legs that cannot be noisy):
the on-runs' ``flight.jsonl`` dump exists, passes the schema lint, and
carries ``flight_sample`` events.

Committed artifact: ``FLIGHT_r12.json`` (full mode, 5 alternating
reps).  ``--smoke`` (2 reps) is the ``tools/perf_gate.py`` leg.

Usage:
    python tools/flight_overhead.py --out FLIGHT_r12.json
    python tools/flight_overhead.py --smoke --out /tmp/flight_smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: the documented noise band the perf gate enforces: a 2-core CI
#: container's run-to-run wall noise dwarfs the ring's actual cost
#: (measured negative-to-low-single-digit %), so the bound is about
#: catching a REAL regression (an accidental lock, an O(n) ring scan
#: per emit), not about resolving the sub-noise true cost
NOISE_BAND_PCT = 10.0


def run_bench(smoke: bool, out_path: "str | None") -> dict:
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
    from land_trendr_tpu.ops.indices import required_bands
    from land_trendr_tpu.runtime import RunConfig, load_stack_dir, run_stack

    sys.path.insert(0, str(REPO / "tools"))
    from check_events_schema import value_lints

    from land_trendr_tpu.obs.events import validate_events_file

    # smoke keeps the rep count minimal: the gate compares MIN-of-reps
    # (a floor estimator — jitter only inflates), so two alternating
    # pairs already bound a real regression while keeping the tier-1
    # wall cost down; full mode's 5 reps feed the committed medians
    reps = 2 if smoke else 5
    # the 4-tile scene is the FLOOR of meaningful run length: shorter
    # runs (tried at 2 tiles) put the per-run wall inside the host's
    # GC/page-cache noise and the gate false-fails — ~2s runs keep the
    # fixed noise under a few percent of wall
    height = 256
    sampler_interval_s = 0.2
    ring_events = 256
    root = tempfile.mkdtemp(prefix="lt_flight_overhead_")
    try:
        stack_dir = os.path.join(root, "stack")
        write_stack(
            stack_dir,
            make_stack(
                SceneSpec(width=256, height=height, year_start=1990,
                          year_end=2013, seed=7)
            ),
        )
        stack = load_stack_dir(stack_dir, bands=required_bands("nbr", ()))

        def one_run(tag: str, flight: bool) -> tuple[float, dict]:
            import gc

            # drain collector garbage BEFORE the timed region: inside
            # the perf gate this bench runs after four others in one
            # process, and an unlucky GC pause landing in an on-rep
            # reads as flight overhead
            gc.collect()
            wd = os.path.join(root, tag)
            cfg = RunConfig(
                params=LTParams(max_segments=4),
                tile_size=128,
                workdir=wd,
                out_dir=wd + "_o",
                telemetry=True,
                flight=flight,
                flight_ring_events=ring_events,
                sampler_interval_s=sampler_interval_s,
            )
            t0 = time.perf_counter()
            summary = run_stack(stack, cfg)
            return time.perf_counter() - t0, summary

        one_run("warmup", flight=False)  # compile outside the medians

        off_s: list[float] = []
        on_s: list[float] = []
        flight_checks: dict = {}
        for rep in range(reps):
            dt_off, _ = one_run(f"off{rep}", flight=False)
            off_s.append(round(dt_off, 3))
            dt_on, summary = one_run(f"on{rep}", flight=True)
            on_s.append(round(dt_on, 3))
            dump = summary.get("telemetry", {}).get("flight")
            if rep == reps - 1:
                # structural: the dump exists, lints clean, and carries
                # the sampler series (checked once — every on-run is the
                # same code path)
                errs = (
                    validate_events_file(dump, extra=value_lints())
                    if dump and os.path.exists(dump)
                    else ["flight dump missing"]
                )
                samples = 0
                events = 0
                if not errs:
                    with open(dump) as f:
                        for line in f:
                            events += 1
                            if '"ev":"flight_sample"' in line:
                                samples += 1
                flight_checks = {
                    "dump_valid": not errs,
                    "dump_errors": errs[:5],
                    "dump_events": events,
                    "samples": samples,
                }

        med_off = statistics.median(off_s)
        med_on = statistics.median(on_s)
        overhead_pct = round(100.0 * (med_on - med_off) / med_off, 2)
        # the GATE metric: min-of-reps.  Scheduler/thermal interference
        # only ever ADDS wall time, so the minima are the noise-robust
        # cost floors — a real regression (a lock on the emit path, an
        # O(n) ring scan) inflates the floor itself, while a CI
        # container's jitter cannot push min_on above min_off by more
        # than the true cost
        min_off, min_on = min(off_s), min(on_s)
        overhead_min_pct = round(100.0 * (min_on - min_off) / min_off, 2)
        result = {
            "what": (
                f"flight recorder + sampler wall overhead: run_stack over "
                f"a 256x{height} synthetic scene ({height // 64} tiles of "
                "128², CPU backend, warm compile), telemetry ON both "
                "sides, flight ring+sampler on vs off, sampler at "
                f"{sampler_interval_s}s (25x the production default "
                f"rate), median of {reps} alternating reps"
            ),
            "scene_px": 256 * height,
            "tiles": height // 64,
            "reps": reps,
            "sampler_interval_s": sampler_interval_s,
            "flight_ring_events": ring_events,
            "off_s": off_s,
            "on_s": on_s,
            "median_off_s": round(med_off, 3),
            "median_on_s": round(med_on, 3),
            "overhead_pct": overhead_pct,
            "min_off_s": round(min_off, 3),
            "min_on_s": round(min_on, 3),
            "overhead_min_pct": overhead_min_pct,
            "noise_band_pct": NOISE_BAND_PCT,
            "flight": flight_checks,
            "smoke": smoke,
            "note": (
                "acceptance bound: overhead_min_pct <= noise_band_pct — "
                "min-of-reps, because container jitter only inflates wall "
                "time while a real regression inflates the floor itself "
                "(ring mirror is a deque append per emit; sampler is a "
                "/proc read per interval).  The median overhead is the "
                "OBS_OVERHEAD_r06-comparable headline.  The dump must "
                "additionally be schema-valid with a non-empty "
                "flight_sample series"
            ),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)
    if out_path:
        from tools._measure import write_json_atomic

        write_json_atomic(out_path, result, trailing_newline=False)
    return result


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="2 reps (the perf-gate leg) instead of 5")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the result JSON here")
    args = ap.parse_args(argv)
    result = run_bench(args.smoke, args.out)
    print(json.dumps(
        {k: result[k] for k in (
            "median_off_s", "median_on_s", "overhead_pct",
            "min_off_s", "min_on_s", "overhead_min_pct",
            "noise_band_pct", "flight",
        )},
        indent=2,
    ))
    ok = (
        result["overhead_min_pct"] <= result["noise_band_pct"]
        and result["flight"].get("dump_valid")
        and result["flight"].get("samples", 0) >= 1
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
