"""Host feed/write path throughput benchmark -> HOSTPATH_r03.json.

SURVEY.md §7 hard-part 4: at the 10M px/s/chip north star the host must
gather ~6 B/pixel-year of DN+QA into device-feed layout (~2.4 GB/s for a
40-year NBR stack) and persist the per-tile outputs.  The TPU chip has
been env-blocked all rounds (BENCH_r03_attempts.log), but every byte of
this path is host code — measurable anywhere.  This tool times the three
host stages in isolation on real-shaped data and reports, per stage, the
single-core px/s and the cores needed to sustain the north star, so the
"feed-bound by design" claim in runtime/driver.py rests on a number.

Stages measured (one 512² tile × 40 years, NBR band set):
  feed.native   - lt_gather_tile (threaded C++; here 1 thread = 1 core)
  feed.numpy    - the pure-NumPy fallback gather
  write.none    - manifest artifact, uncompressed npz (the default)
  write.deflate - manifest artifact, zlib-1 streamed zip
  write.zlib6   - np.savez_compressed (the pre-round-3 behaviour)

Payload realism: the write payload is produced by the actual kernel on
synthetic imagery (ops/tile.process_tile_dn), so compression ratios
reflect real segmentation outputs, not random bytes.

Usage: PYTHONPATH=. python tools/host_path_bench.py [--out HOSTPATH_r03.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io import native
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.ops.tile import process_tile_dn
from land_trendr_tpu.runtime.driver import RunConfig, TileSpec, _feed_tile, _tile_arrays
from land_trendr_tpu.runtime.manifest import TileManifest
from land_trendr_tpu.runtime.stack import stack_from_synthetic

NY = 40
TILE = 512
NORTH_STAR_PX_S = 10e6


def time_fn(fn, reps: int, warmup: int = 1) -> float:
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="HOSTPATH_r03.json")
    ap.add_argument("--scene", type=int, default=2048,
                    help="synthetic scene edge (>= 512 + gather offsets)")
    args = ap.parse_args()

    spec = SceneSpec(width=args.scene, height=args.scene,
                     year_start=1984, year_end=1984 + NY - 1, seed=7)
    stack = stack_from_synthetic(make_stack(spec))
    bands = idx.required_bands("nbr")
    t = TileSpec(tile_id=0, y0=256, x0=256, h=TILE, w=TILE)
    feed_bytes = (len(bands) + 1) * 2 * TILE * TILE * NY  # DN bands + QA, int16

    result: dict = {
        "description": __doc__.split("\n\n")[1].replace("\n", " "),
        "platform": "host (cpu)",
        "nproc": os.cpu_count(),
        "tile": {"size": TILE, "years": NY, "bands": sorted(bands) + ["qa"]},
        "north_star_px_s": NORTH_STAR_PX_S,
        "stages": {},
    }

    def add(name: str, seconds: float, nbytes: int, px: int) -> None:
        px_s = px / seconds
        result["stages"][name] = {
            "s_per_tile": round(seconds, 4),
            "mb_per_s": round(nbytes / seconds / 1e6, 1),
            "px_per_s_per_core": round(px_s, 1),
            "cores_for_north_star": round(NORTH_STAR_PX_S / px_s, 2),
        }

    # --- feed ---------------------------------------------------------
    px = TILE * TILE
    sec = time_fn(lambda: _feed_tile(stack, t, px, bands), reps=10)
    add("feed.native" if native.available() else "feed.numpy", sec, feed_bytes, px)
    if native.available():
        os.environ["LT_NO_NATIVE"] = "1"  # module already loaded; force via monkeypatch
        orig = native._LIB
        native._LIB = None
        try:
            sec = time_fn(lambda: _feed_tile(stack, t, px, bands), reps=5)
            add("feed.numpy", sec, feed_bytes, px)
        finally:
            native._LIB = orig
            del os.environ["LT_NO_NATIVE"]

    # --- feed scaling: the run_stack feed POOL's aggregate rate --------
    # (VERDICT r3 item #3: the 2.4-cores-at-north-star feed budget must be
    # code, not arithmetic — RunConfig.feed_workers is that code; this
    # measures its aggregate throughput at several worker counts.  On a
    # 1-core box the curve is flat by construction; on a device-rate host
    # it scales with cores because the native gather releases the GIL.)
    from concurrent.futures import ThreadPoolExecutor

    grid = args.scene // TILE
    feed_tiles = [
        TileSpec(tile_id=i, y0=(i // grid) * TILE, x0=(i % grid) * TILE,
                 h=TILE, w=TILE)
        for i in range(min(8, grid * grid))
    ]
    scaling = {}
    for workers in (1, 2, 4):
        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(lambda tt: _feed_tile(stack, tt, px, bands), feed_tiles))
            t0 = time.perf_counter()
            list(ex.map(lambda tt: _feed_tile(stack, tt, px, bands), feed_tiles))
            sec = time.perf_counter() - t0
        scaling[str(workers)] = round(len(feed_tiles) * px / sec, 1)
    result["feed_scaling_px_s_aggregate"] = scaling
    result["feed_scaling_note"] = (
        f"aggregate px/s feeding {len(feed_tiles)} distinct tiles through "
        "the RunConfig.feed_workers thread pool; flat on a 1-core box, "
        "scales with cores where the threaded native gather has them"
    )

    # --- real kernel payload for the write stage ----------------------
    dn, qa = _feed_tile(stack, t, px, bands)
    out = process_tile_dn(np.asarray(stack.years, np.int32), dn, qa,
                          index="nbr", ftv_indices=(), params=LTParams())
    jax.block_until_ready(out)
    cfg = RunConfig()
    arrays = _tile_arrays(out, t, cfg)
    payload = int(sum(a.nbytes for a in arrays.values()))
    result["tile"]["write_payload_mb"] = round(payload / 1e6, 1)

    workdir = os.path.join(os.path.dirname(args.out) or ".", ".hostpath_bench")
    sizes = {}
    for mode in ("none", "deflate"):
        m = TileManifest(os.path.join(workdir, mode), "b" * 16)
        m.open(resume=False)
        sec = time_fn(lambda: m.record(0, arrays, {}, compress=mode), reps=3)
        add(f"write.{mode}", sec, payload, px)
        sizes[mode] = os.path.getsize(m.tile_path(0))
    if native.available():
        # the 'none' row above used the native store-zip writer; measure
        # the Python np.savez fallback too so the artifact records both
        # (single-core ~parity is EXPECTED — the native writer's value is
        # releasing the GIL through the payload so write_workers scale)
        m = TileManifest(os.path.join(workdir, "none_py"), "b" * 16)
        m.open(resume=False)
        orig = native._LIB
        native._LIB = None
        try:
            sec = time_fn(lambda: m.record(0, arrays, {}, compress="none"), reps=3)
        finally:
            native._LIB = orig
        add("write.none_python_fallback", sec, payload, px)

    def zlib6():
        np.savez_compressed(os.path.join(workdir, "z6.npz"), **arrays)

    os.makedirs(workdir, exist_ok=True)
    sec = time_fn(zlib6, reps=3)
    add("write.zlib6", sec, payload, px)
    sizes["zlib6"] = os.path.getsize(os.path.join(workdir, "z6.npz"))
    result["artifact_bytes"] = sizes

    import shutil

    shutil.rmtree(workdir, ignore_errors=True)

    from tools._measure import write_json_atomic

    write_json_atomic(args.out, result, trailing_newline=False)
    print(json.dumps(result["stages"], indent=2))


if __name__ == "__main__":
    main()
