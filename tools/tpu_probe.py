"""Tunnel-honest TPU probes: stage attribution, px scaling, primitive costs.

Round-4's kernel diagnosis (`TPU_KERNEL_DIAG_r04.md` §§1,3,7) was driven
by throwaway /tmp scripts; VERDICT r4 Missing #4 asked for the harness to
live in the repo so any future TPU window can reproduce the tables.  All
three probes use the same paired-K chain methodology as ``bench.py``
(`_run_chained`): every timed quantity is the median over window PAIRS of
pair-averaged deltas between long and short ``lax.fori_loop`` chains of
ONE compiled program, so the axon tunnel's multi-second dispatch+fetch
constant cancels and monotone congestion drift cancels within each pair.
Naive ``block_until_ready`` timing is *demonstrated* dishonest through
this tunnel (360× off — diag §1); nothing here uses it.

Usage (on a TPU backend)::

    python tools/tpu_probe.py stages  [--px 262144] [--reps 4] [--out F]
    python tools/tpu_probe.py scaling [--px-list 4096,65536,262144,1048576]
    python tools/tpu_probe.py prims   [--px 65536]

``stages`` times named pipeline variants and prints per-step device
seconds + px/s for each, plus derived attributions (XLA tail cost, fused
in-kernel tail cost, fusion win).  ``scaling`` sweeps the pixel axis on
the production path.  ``prims`` times the primitive ops the round-4
rewrite was justified by (row gather vs one-hot contraction, fills,
atan) at current jax/Mosaic versions.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _chain_time(fn, args, k: int = 16, reps: int = 4):
    """Median pair-averaged per-step seconds for ``fn`` via chained windows.

    ``fn(steps, *args) -> scalar`` must run ``steps`` data-dependent
    applications inside one jitted program (traced fori_loop bound) and
    return a finite probe scalar.  Returns ``(per_step_s, t_long_best)``.
    """
    k_short = max(1, k // 8)

    def timed(steps, i):
        t0 = time.perf_counter()
        r = float(fn(steps, i, *args))
        dt = time.perf_counter() - t0
        if not np.isfinite(r):
            raise RuntimeError("chain probe produced non-finite value")
        return dt

    timed(k, 0)  # warm-up: compile + first run
    best = float("inf")
    deltas = []
    seq = 0
    for _ in range(max(1, reps // 2)):
        seq += 1
        la = timed(k, seq)
        seq += 1
        sa = timed(k_short, seq)
        seq += 1
        sb = timed(k_short, seq)
        seq += 1
        lb = timed(k, seq)
        best = min(best, la, lb)
        deltas.append(((la - sa) + (lb - sb)) / 2.0)
    per_step = float(np.median(deltas)) / (k - k_short)
    return per_step, best


def _population(px: int, ny: int = 40):
    from tools._population import make_population

    rng = np.random.default_rng(7)
    years, vals, mask = make_population(rng, px, ny)
    return years, vals.astype(np.float32), mask


def _stage_variants(px: int, ny: int, block: int):
    """Named pipeline variants, each as ``fn(steps, i, *args) -> scalar``.

    Every variant feeds its despiked output back into the next chain step
    (data dependency — no step can be elided) and reduces outputs whose
    producers span the variant's whole compute, mirroring bench.py.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import (
        jax_segment_pixels_chunked,
        _select_and_assemble,
    )
    from land_trendr_tpu.ops.segment_pallas import (
        family_stats_pallas,
        jax_segment_pixels_pallas_chunked,
    )

    params = LTParams()
    chunk = min(px, 262144)

    def chain(step_fn):
        @jax.jit
        def run(steps, i, y, v, m):
            v = v + jnp.float32(1e-6) * i  # distinct input per window

            def body(_j, carry):
                v_cur, acc = carry
                desp, probe = step_fn(y, v_cur, m)
                return desp, acc + probe

            final, acc = lax.fori_loop(0, steps, body, (v, jnp.float32(0.0)))
            return acc + final[0, 0]

        return run

    def fused_step(y, v, m):
        out = jax_segment_pixels_pallas_chunked(
            y, v, m, params, chunk=chunk, block=block
        )
        probe = out.rmse.sum() + out.n_vertices.sum().astype(out.rmse.dtype)
        return out.despiked, probe

    def family_step(y, v, m):
        desp, vmasks, sses = family_stats_pallas(y, v, m, params, block=block)
        probe = sses.sum() + vmasks.sum(dtype=jnp.float32)
        return desp, probe

    def family_tail_step(y, v, m):
        # the round-4 split: Pallas family kernel + vmapped XLA tail over
        # the HBM-round-tripped (PX, NM, NY) family intermediates
        desp, vmasks, sses = family_stats_pallas(y, v, m, params, block=block)
        t = y.astype(v.dtype)
        mask_b = m.astype(bool) & jnp.isfinite(v)
        out = jax.vmap(
            lambda r, mb, yy, vms, ss: _select_and_assemble(
                t, r, mb, yy, vms, ss, params
            )
        )(v, mask_b, desp, vmasks, sses)
        probe = out.rmse.sum() + out.n_vertices.sum().astype(out.rmse.dtype)
        return out.despiked, probe

    def xla_step(y, v, m):
        out = jax_segment_pixels_chunked(y, v, m, params, chunk=chunk)
        probe = out.rmse.sum() + out.n_vertices.sum().astype(out.rmse.dtype)
        return out.despiked, probe

    return {
        "fused": chain(fused_step),
        "family_only": chain(family_step),
        "family_plus_xla_tail": chain(family_tail_step),
        "xla_kernel": chain(xla_step),
    }


def cmd_stages(args) -> dict:
    import jax

    px, ny, block = args.px, 40, args.block
    years, vals, mask = _population(px, ny)
    dev = jax.devices()[0]
    years_d = jax.device_put(years, dev)
    vals_d = jax.device_put(vals, dev)
    mask_d = jax.device_put(mask, dev)
    out = {
        "probe": "stages",
        "px": px,
        "ny": ny,
        "block": block,
        "chain_k": args.k,
        "device": str(dev),
        "variants": {},
    }
    for name, fn in _stage_variants(px, ny, block).items():
        per_step, t_long = _chain_time(
            fn, (years_d, vals_d, mask_d), k=args.k, reps=args.reps
        )
        out["variants"][name] = {
            "per_step_s": round(per_step, 5),
            "px_per_s": round(px / per_step, 1),
            "t_long_best_s": round(t_long, 4),
        }
        print(f"{name}: {per_step*1e3:.2f} ms/step = {px/per_step/1e6:.2f}M px/s",
              flush=True)
    v = out["variants"]
    if {"fused", "family_only", "family_plus_xla_tail"} <= v.keys():
        out["derived"] = {
            "xla_tail_s": round(
                v["family_plus_xla_tail"]["per_step_s"]
                - v["family_only"]["per_step_s"], 5
            ),
            "in_kernel_tail_s": round(
                v["fused"]["per_step_s"] - v["family_only"]["per_step_s"], 5
            ),
            "fusion_win_s": round(
                v["family_plus_xla_tail"]["per_step_s"]
                - v["fused"]["per_step_s"], 5
            ),
        }
    return out


def cmd_scaling(args) -> dict:
    import jax

    out = {"probe": "scaling", "chain_k": args.k, "points": []}
    for px in args.px_list:
        years, vals, mask = _population(px)
        dev = jax.devices()[0]
        fn = _stage_variants(px, 40, min(args.block, px))["fused"]
        per_step, _ = _chain_time(
            fn,
            (jax.device_put(years, dev), jax.device_put(vals, dev),
             jax.device_put(mask, dev)),
            k=args.k, reps=args.reps,
        )
        out["points"].append(
            {"px": px, "per_step_s": round(per_step, 5),
             "px_per_s": round(px / per_step, 1)}
        )
        print(f"px={px}: {per_step*1e3:.2f} ms/step = {px/per_step/1e6:.2f}M px/s",
              flush=True)
    return out


def cmd_prims(args) -> dict:
    """Primitive microbenchmarks behind the round-4 rewrite decisions."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    px, ny = args.px, 40
    rng = np.random.default_rng(3)
    vals = rng.standard_normal((px, ny)).astype(np.float32)
    idxs = rng.integers(0, ny, (px, ny)).astype(np.int32)
    dev = jax.devices()[0]
    v = jax.device_put(vals, dev)
    ix = jax.device_put(idxs, dev)

    def chain(step):
        @jax.jit
        def run(steps, i, v, ix):
            v = v + jnp.float32(1e-6) * i

            def body(_j, carry):
                cur, acc = carry
                nxt = step(cur, ix)
                return nxt, acc + nxt[0, 0]

            f, acc = lax.fori_loop(0, steps, body, (v, jnp.float32(0.0)))
            return acc + f[0, 0]

        return run

    def gather_rows(cur, ix):
        return jnp.take_along_axis(cur, ix, axis=1)

    def onehot_rows(cur, ix):
        oh = ix[:, :, None] == jnp.arange(cur.shape[1])[None, None, :]
        return jnp.sum(jnp.where(oh, cur[:, None, :], 0.0), axis=-1)

    def fills(cur, ix):
        del ix
        m = cur > 0
        out = jnp.where(m, cur, 0.0)
        has = m
        sh = 1
        while sh < cur.shape[1]:
            out = jnp.where(
                has, out, jnp.pad(out, ((0, 0), (sh, 0)))[:, :-sh]
            )
            has = has | jnp.pad(has, ((0, 0), (sh, 0)))[:, :-sh]
            sh *= 2
        return out

    out = {"probe": "prims", "px": px, "ny": ny, "variants": {}}
    for name, step in [
        ("row_gather", gather_rows),
        ("onehot_contraction", onehot_rows),
        ("log_doubling_fill", fills),
    ]:
        per_step, _ = _chain_time(chain(step), (v, ix), k=args.k, reps=args.reps)
        out["variants"][name] = {"per_step_s": round(per_step, 6)}
        print(f"{name}: {per_step*1e3:.3f} ms/step", flush=True)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("probe", choices=["stages", "scaling", "prims"])
    ap.add_argument("--px", type=int, default=262144)
    ap.add_argument("--block", type=int, default=256)  # production PALLAS_BLOCK
    ap.add_argument("--px-list", type=lambda s: [int(x) for x in s.split(",")],
                    default=[4096, 65536, 262144, 1048576])
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()
    res = {"stages": cmd_stages, "scaling": cmd_scaling, "prims": cmd_prims}[
        args.probe
    ](args)
    line = json.dumps(res)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
