"""Shared helpers for the artifact-producing benchmark tools."""

from __future__ import annotations

import json
import os
import resource


def rss_mb() -> float:
    """This process's ru_maxrss high-water in MB (Linux reports KiB)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def merge_json(path: str, key: str, rec: dict) -> None:
    """Merge ``rec`` under ``key`` into the JSON document at ``path`` and
    echo the addition (the committed-artifact update pattern)."""
    doc = {}
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    doc[key] = rec
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({key: rec}))
