"""Shared helpers for the artifact-producing benchmark tools."""

from __future__ import annotations

import json
import os
import resource


def rss_mb() -> float:
    """This process's ru_maxrss high-water in MB (Linux reports KiB)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def write_text_atomic(path: str, text: str) -> None:
    """Commit ``text`` to ``path`` via the repo's one durable-write
    idiom: write a sibling ``.tmp``, then ``os.replace`` onto the final
    name — rename IS the commit, so a SIGKILL mid-write leaves the old
    artifact intact instead of a torn one (lt-lint LT012's contract)."""
    path = os.fspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def write_json_atomic(
    path: str,
    obj,
    indent: "int | None" = 2,
    trailing_newline: bool = True,
) -> None:
    """JSON flavor of :func:`write_text_atomic` — the benchmark/report
    ``--out`` artifacts the perf gate and committed baselines consume.
    ``indent``/``trailing_newline`` mirror each tool's historical output
    bytes so regenerated artifacts diff clean."""
    text = json.dumps(obj, indent=indent)
    if trailing_newline:
        text += "\n"
    write_text_atomic(path, text)


def merge_json(path: str, key: str, rec: dict) -> None:
    """Merge ``rec`` under ``key`` into the JSON document at ``path`` and
    echo the addition (the committed-artifact update pattern)."""
    doc = {}
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    doc[key] = rec
    write_json_atomic(path, doc, indent=1)
    print(json.dumps({key: rec}))
