"""Same-host architecture speedup: reference-style per-pixel Python vs
the batched TPU-native kernel.

The reference executes LandTrendr as scalar per-pixel Python (NumPy
float64) under Hadoop streaming — one map task per pixel (SURVEY.md §2
L4/L3, BASELINE.json north star: "emitting one map task per pixel").
This repo's `models/oracle.py` IS that execution style, minus Hadoop:
the same per-pixel scalar pipeline the reference's `PixelSegmenter`
runs, written against the public algorithm spec.  Timing it against
`jax_segment_pixels` on the SAME host CPU therefore measures the
architecture speedup of the rebuild — batched fixed-shape XLA vs
per-pixel scalar Python — with zero hardware advantage.

The oracle rate is an UPPER bound on the reference's end-to-end rate:
Hadoop adds process spawn, text serialization, and shuffle on top of
the per-pixel math (SURVEY.md §4: "the entire per-pixel cost ... is
wrapped in process spawn + text serialization + shuffle overhead"),
so the true reference would be slower than the number used here.

Writes ONE JSON artifact:

    oracle_px_s          — per-pixel scalar f64 rate (reference style)
    kernel_cpu_px_s      — batched f32 kernel, same host CPU, loop mode
    speedup_same_host    — kernel_cpu_px_s / oracle_px_s
    tpu_px_s, speedup_tpu_vs_reference_style
                         — cross-referenced from BENCH_r{R}.json when a
                           real accelerator number exists there

Usage: python tools/arch_speedup.py [oracle_px] [kernel_px] [out.json]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import make_series  # noqa: E402  (same population as the headline bench)


def time_oracle(px: int, ny: int) -> tuple[float, float]:
    """(seconds, fit_rate) for `px` pixels through the scalar oracle."""
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.models.oracle import segment_series

    params = LTParams()
    years, vals, mask = make_series(px, ny)
    years64 = years.astype(np.float64)
    # one un-timed pixel: import/first-call setup out of the window
    segment_series(years64, vals[0], mask[0], params)
    n_fit = 0
    t0 = time.perf_counter()
    for i in range(px):
        r = segment_series(years64, vals[i], mask[i], params)
        n_fit += bool(r.model_valid)
    dt = time.perf_counter() - t0
    return dt, n_fit / px


def time_kernel_cpu(px: int, ny: int, reps: int = 3) -> tuple[float, float]:
    """(best seconds, fit_rate) for the batched f32 kernel on host CPU."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels

    params = LTParams()
    years, vals, mask = make_series(px, ny)
    run = jax.jit(lambda y, v, m: jax_segment_pixels(y, v, m, params))
    out = run(years, vals, mask)
    jax.block_until_ready(out)
    fit_rate = float(np.asarray(out.model_valid).mean())
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(years, vals, mask)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, fit_rate


def main() -> int:
    oracle_px = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    kernel_px = int(sys.argv[2]) if len(sys.argv) > 2 else 65536
    out_path = sys.argv[3] if len(sys.argv) > 3 else "ARCH_SPEEDUP.json"
    ny = 40

    oracle_s, oracle_fit = time_oracle(oracle_px, ny)
    oracle_px_s = oracle_px / oracle_s
    kernel_s, kernel_fit = time_kernel_cpu(kernel_px, ny)
    kernel_px_s = kernel_px / kernel_s

    rec = {
        "metric": "architecture_speedup_same_host",
        "oracle_px": oracle_px,
        "oracle_px_s": round(oracle_px_s, 1),
        "oracle_fit_rate": round(oracle_fit, 4),
        "kernel_px": kernel_px,
        "kernel_cpu_px_s": round(kernel_px_s, 1),
        "kernel_fit_rate": round(kernel_fit, 4),
        "speedup_same_host": round(kernel_px_s / oracle_px_s, 1),
        "years": ny,
        "nproc": os.cpu_count(),
        "note": (
            "oracle = reference-style per-pixel scalar f64 Python "
            "(models/oracle.py — the execution model of the reference's "
            "PixelSegmenter under Hadoop, minus Hadoop's spawn/serialize/"
            "shuffle overhead, so an UPPER bound on the reference's "
            "rate); kernel = batched f32 jax_segment_pixels on the SAME "
            "host CPU (loop mode, best of 3). Populations identical "
            "(bench.make_series)."
        ),
    }

    # cross-reference the TPU number when a real one exists
    round_id = os.environ.get("LT_ROUND", "04")
    bench_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        f"BENCH_r{round_id}.json",
    )
    try:
        bench = json.load(open(bench_path))
        if bench.get("device_platform") not in (None, "cpu") and bench.get(
            "value", 0
        ) > 0:
            rec["tpu_px_s"] = bench["value"]
            rec["tpu_bench_note"] = bench.get("note", "")
            rec["speedup_tpu_vs_reference_style"] = round(
                bench["value"] / oracle_px_s, 1
            )
    except (OSError, ValueError):
        pass

    from tools._measure import write_json_atomic

    write_json_atomic(out_path, rec, trailing_newline=False)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    main()
