"""Fault-injection soak: every seam fired, artifacts byte-identical.

The robustness acceptance gate (ISSUE 5): for every injection seam of
:mod:`land_trendr_tpu.runtime.faults`, run a seeded schedule that fires
exactly there and assert the run recovers with **byte-identical tile
artifacts** to a clean run — either in-run (retry ladder, feed retry,
cache bypass, fetch demotion) or across an abort + resume (manifest
persist faults, quarantine).  Determinism is the whole point: the same
schedule replays the same faults at the same invocations, so a recovery
regression fails this gate instead of waiting for real hardware to fail.

Two scene tracks:

* **eager** (in-RAM synthetic stack): the driver seams — ``feed``,
  ``dispatch``, ``compute.wait``, ``fetch.wait`` and ``upload.wait``
  (packed paths forced; one schedule per direction also driving the
  demotion threshold), ``manifest.record`` (ENOSPC → abort → resume),
  ``manifest.torn`` (post-record truncation → resume readability
  check), and a quarantine schedule (persistent tile fault → run
  continues → resume completes it);
The eager track also carries the straggler observability case: a
``slow`` fault parked on one tile's compute wait must emit a
``tile_straggler`` event (``duration_s ≥ threshold_s``) in the run's
telemetry stream while artifacts stay byte-identical — detection
observes, never steers.

* **lazy** (windowed C2 per-band stack): the decode seams —
  ``feed.decode`` (transient window-read fault → feed retry),
  ``cache.corrupt`` (poisoned cached block → invalidate + re-decode),
  and ``store.corrupt`` (poisoned persistent-store block → both tiers
  invalidated + re-decode).

``--smoke`` is the seconds-scale tier-1 mode (``tests/test_faults.py``
runs it in-process); the full mode adds probabilistic multi-seed rounds
and writes a ``FAULTSOAK_*.json`` artifact.

    python tools/fault_soak.py --smoke
    python tools/fault_soak.py --seeds 5 --out FAULTSOAK_r09.json
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: Every registered seam a soak case exercises, as data — the coverage
#: half of lt-lint LT011's three-way cross-check (the ``NONNEG_FIELDS``
#: shared-table pattern): the linter literal-evals this table (it never
#: imports a numpy-loading tool) and flags any ``runtime/faults.py``
#: ``SEAMS`` entry missing here as an uncovered seam, and any entry
#: here that is not registered as stale.  ``tests/test_faults.py`` pins
#: the table against the schedules the cases actually arm from the
#: other side, so a seam cannot be "covered" by table edit alone.
SOAK_COVERED_SEAMS = (
    "feed",                # eager feed_transient
    "feed.decode",         # lazy decode_transient
    "cache.corrupt",       # lazy cache_corrupt
    "store.corrupt",       # lazy store_corrupt
    "upload.wait",         # eager upload_wait_fault / upload_demotion
    "dispatch",            # eager dispatch_fault / quarantine
    "compute.wait",        # eager compute_wait_fault / straggler_slow
    "fetch.wait",          # eager fetch_wait_fault / fetch_demotion
    "manifest.record",     # eager manifest_enospc (abort → resume)
    "manifest.torn",       # eager manifest_torn (abort → resume)
    "lease.acquire",       # eager lease_acquire_fault
    "lease.steal",         # eager lease_forced_steal
    "lease.expire",        # eager lease_forced_steal
    "merge.peer",          # merge_peer_partial (dead-peer bounded wait)
    "serve.submit",        # serve submit_reject_and_sibling_quarantine
    "serve.job",           # serve job_fault_then_resubmit
    "debug.profile",       # serve debug_stacks_under_hang_and_profile_fault
    "obs.publish",         # fleet telemetry case (swallowed STOP flush)
    "history.append",      # fleet telemetry case (lossy ring append)
    "router.forward",      # router forward-fault re-route case
    "replica.health",      # router health-flap case
    "tune.probe",          # tune_probe_fault (degraded-profile run)
    "loadgen.tick",        # loadgen churn case
    "batch.pack",          # batch pack/demux fault case
    "batch.demux",         # batch pack/demux fault case
    "router.journal",      # journal append fault → 503, resubmit lands
    "router.recover",      # recovery probe fault → requeue + resume
)

import numpy as np  # noqa: E402


def _digest_workdir(workdir: str) -> dict:
    """tile_id → {array name → sha256 of raw bytes} for every artifact.

    Array-content identity, not file identity: the ``.npz`` container
    embeds zip metadata (mtimes) that legitimately differs run to run,
    while the contract is about the DATA a resume/assembly consumes.
    """
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(np.ascontiguousarray(z[name]).tobytes())
                .hexdigest()
                for name in sorted(z.files)
            }
    return out


def _run(stack, cfg):
    from land_trendr_tpu.runtime import run_stack

    return run_stack(stack, cfg)


def _assert_two_hop_trace(
    trace_id: str, roots: list, expect_failed_first: bool
) -> dict:
    """Request-tracing assertions for a re-routed job (ISSUE 15): the
    streams under ``roots`` must assemble into ONE complete trace with
    exactly two forward hops under ``trace_id`` — the first failed
    (forward fault) or succeeded (replica killed after accepting) per
    ``expect_failed_first`` — and a blame partition that sums to the
    router-observed latency with the re-route visible in it.  Returns
    the assembled record (the soak report carries its highlights)."""
    from tools.lt_request import expand_paths

    from land_trendr_tpu.obs.reqtrace import assemble_request

    # the CLI's expansion (fleet-layout discovery + ordered dedupe) —
    # the soak must scan exactly the file set an operator's lt_request
    # invocation would
    files = expand_paths([str(r) for r in roots])
    rec = assemble_request(files, trace_id)
    if not rec["complete"]:
        raise AssertionError(
            f"reqtrace: trace {trace_id} did not assemble complete "
            f"from {len(files)} stream(s): {rec}"
        )
    hops = rec["hops"]
    if expect_failed_first:
        # the deterministic forward-fault schedule: exactly two hops,
        # the faulted try then the re-route
        if len(hops) != 2:
            raise AssertionError(
                f"reqtrace: expected BOTH forward hops under one "
                f"trace_id, got {hops}"
            )
        if hops[0]["ok"] is not False or hops[1]["ok"] is not True:
            raise AssertionError(
                f"reqtrace: expected failed-then-ok hops, got {hops}"
            )
    else:
        # the SIGKILL path: >= 2 hops (a poll-retry may add one), the
        # journey starting on the killed replica and ending elsewhere
        if len(hops) < 2:
            raise AssertionError(
                f"reqtrace: expected a re-route hop under one "
                f"trace_id, got {hops}"
            )
        if hops[0]["replica"] == hops[-1]["replica"]:
            raise AssertionError(
                f"reqtrace: re-route landed on the SAME replica: {hops}"
            )
    if abs(rec["blame_sum_s"] - rec["latency_s"]) > 5e-3:
        raise AssertionError(
            f"reqtrace: blame {rec['blame']} sums to "
            f"{rec['blame_sum_s']} vs latency {rec['latency_s']}"
        )
    # the re-route is IN the blame: the second hop's queue wait and
    # both forwards were partitioned out of the latency
    if rec["blame"].get("forward", 0.0) <= 0:
        raise AssertionError(
            f"reqtrace: no forward share in the blame: {rec['blame']}"
        )
    return rec


@dataclasses.dataclass
class Case:
    name: str
    schedule: str
    cfg_kw: dict
    #: "inrun" = must complete without raising; "resume" = first run may
    #: abort, a clean resume must complete; "quarantine" = first run
    #: completes WITH quarantined tiles, the resume finishes them
    mode: str = "inrun"


def _eager_cases(retries: int) -> list[Case]:
    packed = {"fetch_packed": True}
    return [
        Case("feed_transient", "seed=1,feed@1=io", {}),
        Case("dispatch_fault", "seed=1,dispatch@1", {}),
        Case("compute_wait_fault", "seed=1,compute.wait@1", {}),
        Case("fetch_wait_fault", "seed=1,fetch.wait@1=io", dict(packed)),
        Case(
            "fetch_demotion",
            "seed=1,fetch.wait@0*3=io",
            {**packed, "max_retries": 4},
        ),
        # the upload mirror: an error surfacing through the packed
        # host→device wait re-enters the same ladder; repeated failures
        # demote to the per-array sync dispatch — artifacts identical
        Case("upload_wait_fault", "seed=1,upload.wait@1", {"upload_packed": True}),
        Case(
            "upload_demotion",
            "seed=1,upload.wait@0*3",
            {"upload_packed": True, "max_retries": 4},
        ),
        # elastic lease queue: the first acquisition fails at the
        # lease.acquire seam — the host logs, backs off one cycle, and
        # retries; the run completes with identical artifacts (the lease
        # log is pure coordination, never a correctness surface)
        Case(
            "lease_acquire_fault",
            "seed=1,lease.acquire@0=io",
            {"lease_batch": 2, "lease_ttl_s": 10.0},
        ),
        Case("manifest_enospc", "seed=1,manifest.record@1=enospc", {}, "resume"),
        Case("manifest_torn", "seed=1,manifest.torn@1", {}, "resume"),
        Case(
            "quarantine",
            f"seed=1,dispatch@1*{retries + 1}",
            {"quarantine_tiles": True},
            "quarantine",
        ),
    ]


_LAZY_CASES = [
    Case("decode_transient", "seed=1,feed.decode@2=value", {}),
    Case("cache_corrupt", "seed=1,cache.corrupt@1", {}),
    # persistent-store corruption: the RAM tier is OFF so store-served
    # blocks are demand traffic; a poisoned one is invalidated in BOTH
    # tiers and re-decoded — byte-identical artifacts like every seam
    Case(
        "store_corrupt",
        "seed=1,store.corrupt@1",
        {"feed_cache_mb": 0, "ingest_store_mb": 64},
    ),
]


def _make_eager(size_y: int, size_x: int):
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import stack_from_synthetic

    spec = SceneSpec(
        width=size_x, height=size_y, year_start=1990, year_end=2013, seed=11
    )
    return stack_from_synthetic(make_stack(spec))


def _make_lazy(root: str, size: int):
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack_c2
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    spec = SceneSpec(
        width=size, height=size, year_start=2000, year_end=2006, seed=7
    )
    write_stack_c2(root, make_stack(spec))
    return open_stack_dir_c2_lazy(root, bands=("nir", "swir2"))


def soak(
    smoke: bool = True,
    seeds: int = 3,
    keep: "str | None" = None,
    verbose: bool = True,
) -> dict:
    """Run the soak matrix; returns the result report (raises on the
    first broken recovery so failures carry a full traceback)."""
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.runtime import RunConfig

    retries = 2
    base_kw = dict(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=20,
        max_retries=retries,
        retry_backoff_s=0.0,  # the soak pins recovery, not pacing
    )
    root = Path(keep or tempfile.mkdtemp(prefix="lt_fault_soak_"))
    root.mkdir(parents=True, exist_ok=True)
    report: dict = {"smoke": smoke, "cases": []}

    def run_track(track: str, stack, cases: list[Case], tile_size: int) -> None:
        kw = {**base_kw, "tile_size": tile_size}
        clean_wd = str(root / f"{track}_clean")
        _run(stack, RunConfig(workdir=clean_wd, out_dir=clean_wd + "_o", **kw))
        clean = _digest_workdir(clean_wd)
        for case in cases:
            wd = str(root / f"{track}_{case.name}")
            cfg = RunConfig(
                workdir=wd,
                out_dir=wd + "_o",
                fault_schedule=case.schedule,
                **{**kw, **case.cfg_kw},
            )
            rec = {"track": track, "case": case.name, "schedule": case.schedule}
            aborted = False
            try:
                summary = _run(stack, cfg)
            except Exception as e:
                if case.mode != "resume":
                    raise
                aborted = True
                rec["abort_error"] = f"{type(e).__name__}: {e}"
                # the recovery leg these seams pin: a plain resume
                summary = _run(
                    stack,
                    RunConfig(workdir=wd, out_dir=wd + "_o", **{**kw, **case.cfg_kw}),
                )
            if case.mode == "quarantine":
                if not summary["tiles_quarantined"]:
                    raise AssertionError(
                        f"{case.name}: expected quarantined tiles, got none"
                    )
                rec["quarantined"] = summary["tiles_quarantined"]
                summary = _run(
                    stack, RunConfig(workdir=wd, out_dir=wd + "_o", **kw)
                )
                if summary["tiles_quarantined"]:
                    raise AssertionError(
                        f"{case.name}: resume left tiles quarantined"
                    )
            if case.mode == "resume" and not aborted:
                raise AssertionError(
                    f"{case.name}: schedule {case.schedule!r} did not abort "
                    "the first run — the seam no longer fires there"
                )
            got = _digest_workdir(wd)
            if got != clean:
                raise AssertionError(
                    f"{case.name}: artifacts differ from the clean run "
                    f"(schedule {case.schedule!r})"
                )
            rec["artifacts_identical"] = True
            report["cases"].append(rec)
            if verbose:
                print(f"  ok: {track}/{case.name} ({case.schedule})")

    def run_straggler_case(stack) -> None:
        """Observability contract under an injected straggler (ISSUE 10):
        a ``slow`` fault parked on one tile's compute wait must surface
        as a ``tile_straggler`` event in the run's telemetry stream —
        with ``duration_s`` over its ``threshold_s``, the value lint's
        invariant — while the run completes with artifacts byte-identical
        to the clean run (a straggler is an observation, never a
        behavior change)."""
        wd = str(root / "eager_straggler")
        cfg = RunConfig(
            workdir=wd,
            out_dir=wd + "_o",
            # invocation 4 = the 5th tile's sanctioned compute wait on
            # this 6-tile stack: enough completions before it to seed
            # the rolling median, and the 1s park dwarfs k x median
            fault_schedule="seed=1,compute.wait@4=slow:1.0",
            telemetry=True,
            straggler_k=2.0,
            straggler_min_tiles=2,
            **base_kw,
        )
        _run(stack, cfg)
        events = [
            json.loads(line)
            for line in (Path(wd) / "events.jsonl").read_text().splitlines()
            if line.strip()
        ]
        stragglers = [e for e in events if e.get("ev") == "tile_straggler"]
        if not stragglers:
            raise AssertionError(
                "slow fault on compute.wait@4 produced no tile_straggler "
                "event — the detector no longer sees the parked tile"
            )
        bad = [
            e for e in stragglers
            if not e["duration_s"] >= e["threshold_s"] > 0
        ]
        if bad:
            raise AssertionError(
                f"tile_straggler events violate duration >= threshold > 0: "
                f"{bad}"
            )
        got = _digest_workdir(wd)
        clean = _digest_workdir(str(root / "eager_clean"))
        if got != clean:
            raise AssertionError(
                "straggler run artifacts differ from the clean run — the "
                "verdict changed behavior"
            )
        report["cases"].append(
            {
                "track": "eager",
                "case": "straggler_slow",
                "schedule": cfg.fault_schedule,
                "straggler_events": len(stragglers),
                "straggler_tiles": sorted({e["tile_id"] for e in stragglers}),
                "artifacts_identical": True,
            }
        )
        if verbose:
            print(
                f"  ok: eager/straggler_slow ({cfg.fault_schedule}; "
                f"{len(stragglers)} tile_straggler event(s))"
            )

    def run_lease_steal_case(stack) -> None:
        """Deterministic steal-under-a-living-owner (the ``lease.expire``
        and ``lease.steal`` seams): the workdir is pre-seeded with a
        LIVE foreign lease — a ghost owner holding tile 0 on a 1-hour
        TTL under the run's own manifest fingerprint — so the elastic
        runner starts blocked on that tile.  ``lease.expire%1.0`` forces
        every blocked probe to read expired; the first forced steal the
        runner actually picks hits ``lease.steal@0=io`` — the acquire
        raises, the host backs off and retries (the documented lease
        contract) — and the retry steals for real.  One process, no
        SIGKILL choreography (that is full-mode ``lease_kill_steal``),
        completes without a resume, artifacts byte-identical."""
        wd = str(root / "eager_lease_steal")
        cfg = RunConfig(
            workdir=wd,
            out_dir=wd + "_o",
            fault_schedule="seed=1,lease.expire%1.0,lease.steal@0=io",
            lease_batch=2,
            lease_ttl_s=10.0,
            **base_kw,
        )
        Path(wd).mkdir(parents=True, exist_ok=True)
        header = {
            "kind": "header",
            "fingerprint": cfg.fingerprint(stack),
            # must match the resuming run's execution context exactly or
            # open(resume=True) rejects the workdir as foreign
            "context": {"mesh_devices": 1, "impl": "xla"},
            "run_id": "ghost-run",
        }
        ghost = {
            "kind": "lease",
            "tile_id": 0,
            "gen": 0,
            "owner": "ghost:1:g",
            "host": "ghost",
            "pid": 1,
            "ttl_s": 3600.0,
            "t_wall": time.time(),
            "mode": "claim",
        }
        # a pre-seeded fixture, not a durable artifact: the run's own
        # manifest machinery takes over the file from here
        (Path(wd) / "manifest.jsonl").write_text(  # lt: noqa[LT012]
            json.dumps(header) + "\n" + json.dumps(ghost) + "\n"
        )
        summary = _run(stack, cfg)
        seams = {f["seam"] for f in summary.get("faults_injected", [])}
        if not {"lease.expire", "lease.steal"} <= seams:
            raise AssertionError(
                "lease_forced_steal: expected both lease.expire and "
                f"lease.steal to fire, got {sorted(seams)}"
            )
        got = _digest_workdir(wd)
        clean = _digest_workdir(str(root / "eager_clean"))
        if got != clean:
            raise AssertionError(
                "lease_forced_steal: artifacts differ from the clean run"
            )
        report["cases"].append({
            "track": "eager",
            "case": "lease_forced_steal",
            "schedule": cfg.fault_schedule,
            "seams_fired": sorted(
                s for s in seams if s.startswith("lease.")
            ),
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: eager/lease_forced_steal ({cfg.fault_schedule})"
            )

    def run_merge_peer_case() -> None:
        """Dead-peer merge semantics (the ``merge.peer`` seam): with the
        seam armed at probability 1.0 every tail probe reads
        not-terminal, so the primary's bounded wait expires and it
        returns the PARTIAL merge of the streams that exist — never a
        hang, never a crash.  Disarmed, the same merge resolves
        immediately with every host terminal."""
        from land_trendr_tpu.obs.events import EventLog, events_path
        from land_trendr_tpu.parallel.multihost import merge_host_event_logs
        from land_trendr_tpu.runtime import faults

        wd = str(root / "merge_peer")
        Path(wd).mkdir(parents=True, exist_ok=True)
        for i in range(2):
            with EventLog(events_path(wd, i, 2)) as elog:
                elog.run_start(
                    fingerprint="f" * 16, process_index=i, process_count=2,
                    tiles_total=2, tiles_todo=2, tiles_skipped_resume=0,
                    mesh_devices=1, impl="xla",
                )
                elog.emit(
                    "run_done", status="ok", tiles_done=1, pixels=10,
                    wall_s=0.1, px_per_s=100.0, fit_rate=1.0,
                )
        plan = faults.activate(faults.parse_schedule("seed=1,merge.peer%1.0"))
        try:
            t0 = time.monotonic()
            merged = merge_host_event_logs(
                wd, expect_hosts=2, timeout_s=0.4, poll_s=0.05
            )
            waited = time.monotonic() - t0
        finally:
            faults.deactivate()
        if "merge.peer" not in {s for s, _i, _k in plan.injected()}:
            raise AssertionError(
                "merge_peer_partial: the armed seam never fired — "
                f"{plan.injected()}"
            )
        if not 0.3 < waited < 30.0:
            raise AssertionError(
                f"merge_peer_partial: expected the bounded wait to "
                f"expire (~0.4s), waited {waited:.3f}s"
            )
        if len(merged) != 2:
            raise AssertionError(
                f"merge_peer_partial: partial merge should still fold "
                f"what exists (2 streams), got {len(merged)}"
            )
        merged = merge_host_event_logs(
            wd, expect_hosts=2, timeout_s=5.0, poll_s=0.05
        )
        if [m["status"] for m in merged] != ["ok", "ok"]:
            raise AssertionError(
                f"merge_peer_partial: clean merge did not resolve both "
                f"hosts terminal: {merged}"
            )
        report["cases"].append({
            "track": "merge",
            "case": "merge_peer_partial",
            "schedule": "seed=1,merge.peer%1.0",
            "waited_s": round(waited, 3),
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: merge/merge_peer_partial (bounded wait "
                f"{waited:.2f}s, partial merge folded)"
            )

    def run_fleet_case(stack) -> None:
        """Fleet-telemetry failure semantics (ISSUE 11): with the
        ``obs.publish`` seam armed, the run's START snapshot lands
        (invocation 0), the terminal STOP flush faults (invocation 1)
        and is swallowed — the run completes with artifacts
        byte-identical to the clean run, the host simply reads as a
        late/stale snapshot.  The aggregator over the telemetry dir —
        with a TORN snapshot planted beside the real one — flags the
        torn host corrupt and the real host's fold stays intact (never
        a crash, never silent omission).  Then the ``history.append``
        seam over a live ring: the faulted append loses ONE sample, the
        ring reads back consistent and reopens clean."""
        from land_trendr_tpu.obs import aggregate
        from land_trendr_tpu.obs.history import HistoryRing
        from land_trendr_tpu.runtime import faults

        wd = str(root / "eager_fleet")
        cfg = RunConfig(
            workdir=wd,
            out_dir=wd + "_o",
            # the START snapshot publishes during telemetry construction,
            # BEFORE the driver arms the plan (so it lands, un-indexed);
            # with a 60s interval no loop beat fires on this seconds-scale
            # run — seam invocation 0 is exactly the terminal STOP flush
            fault_schedule="seed=1,obs.publish@0=io",
            telemetry=True,
            publish=True,
            publish_interval_s=60.0,
            **base_kw,
        )
        summary = _run(stack, cfg)
        fired = [
            f for f in summary.get("faults_injected", [])
            if f["seam"] == "obs.publish"
        ]
        if not fired:
            raise AssertionError(
                "obs.publish@0 never fired — the seam no longer guards "
                "the publisher"
            )
        tel_dir = Path(wd) / "telemetry"
        snaps = sorted(tel_dir.glob("*.snap.json"))
        if len(snaps) != 1:
            raise AssertionError(
                f"expected exactly the start snapshot, found "
                f"{[s.name for s in snaps]}"
            )
        # a torn snapshot IS the fixture: the aggregator must flag it
        # corrupt without crashing the fold — atomicity would defeat it
        # lt: noqa[LT012]
        (tel_dir / "torn-host.4242.snap.json").write_text(
            '{"schema": 1, "host": "torn-host", "pid": 4242, "t_w'
        )
        view = aggregate.fold_dir(str(tel_dir))
        if view["counts"]["corrupt"] != 1 or view["counts"]["folded"] != 1:
            raise AssertionError(
                f"aggregate must flag the torn snap and fold the real "
                f"host: {view['counts']}"
            )
        tiles = [
            m for m in view["metrics"] if m["name"] == "lt_tiles_done_total"
        ]
        # the faulted beat was the TERMINAL flush, so the surviving
        # snapshot is the start-of-run one: its counters fold (proving
        # the torn sibling never corrupted the merge) at their honest
        # pre-run value of zero
        if not tiles or tiles[0]["value"] != 0:
            raise AssertionError(
                f"the surviving host's counters did not fold cleanly: "
                f"{tiles}"
            )
        got = _digest_workdir(wd)
        clean = _digest_workdir(str(root / "eager_clean"))
        if got != clean:
            raise AssertionError(
                "fleet-publish run artifacts differ from the clean run — "
                "the publisher changed behavior"
            )
        # history.append seam: one lost sample, never a corrupted ring
        hist_dir = str(root / "fleet_history")
        plan = faults.activate(
            faults.parse_schedule("seed=1,history.append@1=io")
        )
        try:
            ring = HistoryRing(hist_dir, samples_per_segment=4)
            lost = 0
            for i in range(6):
                try:
                    ring.append({"t": float(i), "hosts": 1, "stale_hosts": 0})
                except OSError:
                    lost += 1
            ring.close()
        finally:
            faults.deactivate()
        if lost != 1:
            raise AssertionError(
                f"history.append@1 should cost exactly one sample, lost "
                f"{lost}"
            )
        ring2 = HistoryRing(hist_dir)
        samples, malformed = ring2.read()
        ring2.close()
        if len(samples) != 5 or malformed:
            raise AssertionError(
                f"ring after a faulted append: {len(samples)} samples "
                f"(want 5), {malformed} malformed"
            )
        report["cases"].append(
            {
                "track": "eager",
                "case": "fleet_publish_and_history_faults",
                "schedule": cfg.fault_schedule,
                "torn_snap_flagged": True,
                "history_samples_lost": lost,
                "artifacts_identical": True,
            }
        )
        if verbose:
            print(
                "  ok: eager/fleet_publish_and_history_faults "
                f"({cfg.fault_schedule} + history.append@1=io)"
            )

    def run_serve_track() -> None:
        """Serve-mode failure semantics: with the server's ONE armed
        plan firing at ``serve.submit`` (first submission rejected, the
        server lives) and at ``dispatch`` (job A's first tile exhausts
        its retries and is quarantined → the job reports
        ``retries_exhausted``), sibling job B still completes with
        artifacts byte-identical to a plain clean run — a failing job
        never takes down the server or its neighbours."""
        from land_trendr_tpu.io.synthetic import (
            SceneSpec,
            make_stack,
            write_stack,
        )
        from land_trendr_tpu.ops.indices import required_bands
        from land_trendr_tpu.runtime import load_stack_dir
        from land_trendr_tpu.serve import (
            Rejection,
            SegmentationServer,
            ServeConfig,
        )

        sdir = str(root / "serve_stack")
        write_stack(
            sdir,
            make_stack(
                SceneSpec(
                    width=48, height=40, year_start=1990, year_end=2013,
                    seed=11,
                )
            ),
        )
        # the reference digest: a plain clean run over the SAME on-disk
        # stack (the serve jobs must reproduce it byte for byte)
        clean_wd = str(root / "serve_clean")
        _run(
            load_stack_dir(sdir, bands=required_bands("nbr", ())),
            RunConfig(workdir=clean_wd, out_dir=clean_wd + "_o", **base_kw),
        )
        clean = _digest_workdir(clean_wd)

        # dispatch invocation 0 is job A's warm probe (program-cache
        # miss); its first real tile then burns attempts 1..retries+1
        schedule = f"seed=1,serve.submit@0=io,dispatch@1*{retries + 1}"
        server = SegmentationServer(
            ServeConfig(
                workdir=str(root / "serve_srv"),
                max_jobs=2,
                feed_cache_mb=64,
                fault_schedule=schedule,
            )
        )
        job = {
            "stack_dir": sdir,
            "tile_size": base_kw["tile_size"],
            "params": {"max_segments": 4, "vertex_count_overshoot": 2},
            "max_retries": retries,
            "run_overrides": {"retry_backoff_s": 0.0},
        }
        try:
            server.submit(dict(job))
        except Rejection as e:
            if e.reason != "submit_error":
                raise AssertionError(
                    f"serve.submit seam: expected submit_error, got "
                    f"{e.reason}"
                )
        else:
            raise AssertionError(
                "serve.submit@0 did not reject the first submission — "
                "the seam no longer fires there"
            )
        a = server.submit({**job, "quarantine_tiles": True})
        b = server.submit(dict(job))
        server.serve_forever()  # drains both jobs, then shuts down
        sa = server.job_status(a["job_id"])
        sb = server.job_status(b["job_id"])
        if sa["state"] != "retries_exhausted" or not sa["summary"][
            "tiles_quarantined"
        ]:
            raise AssertionError(
                f"job A: expected retries_exhausted with quarantined "
                f"tiles, got {sa['state']} "
                f"({sa.get('summary', {}).get('tiles_quarantined')})"
            )
        if sb["state"] != "done":
            raise AssertionError(
                f"job B: expected done beside the failing sibling, got "
                f"{sb['state']} ({sb.get('error')})"
            )
        got = _digest_workdir(sb["workdir"])
        if got != clean:
            raise AssertionError(
                "serve job B artifacts differ from the clean run"
            )
        report["cases"].append(
            {
                "track": "serve",
                "case": "submit_reject_and_sibling_quarantine",
                "schedule": schedule,
                "job_a": sa["state"],
                "job_b": sb["state"],
                "artifacts_identical": True,
            }
        )
        if verbose:
            print(f"  ok: serve/submit_reject_and_sibling_quarantine "
                  f"({schedule})")

        # debug-surface soak: with a hang fault wedging the dispatcher,
        # /debug/stacks must still answer (and show the wedged frame),
        # and a debug.profile fault must fail the CAPTURE
        # (profile_captured ok=false) — never the job or the server
        import threading as _threading
        import urllib.request as _request

        schedule2 = "seed=2,dispatch@0*2=hang:1.0,debug.profile@0"
        server2 = SegmentationServer(
            ServeConfig(
                workdir=str(root / "serve_dbg"),
                max_jobs=1,
                feed_cache_mb=64,
                sampler_interval_s=0.2,
                fault_schedule=schedule2,
            )
        )
        c = server2.submit(dict(job))
        t = _threading.Thread(target=server2.serve_forever)
        t.start()
        try:
            base = f"http://127.0.0.1:{server2.port}"

            def _get(path: str):
                with _request.urlopen(base + path, timeout=30) as r:
                    return json.loads(r.read())

            deadline = time.monotonic() + 60
            wedged = False
            while time.monotonic() < deadline and not wedged:
                stacks = _get("/debug/stacks")["threads"]
                wedged = any(
                    any("_hang" in line for line in frames)
                    for frames in stacks.values()
                )
                if not wedged:
                    time.sleep(0.05)
            if not wedged:
                raise AssertionError(
                    "/debug/stacks never showed the dispatcher wedged in "
                    "the armed hang fault"
                )
            req = _request.Request(
                base + "/debug/profile",
                data=b'{"duration_s": 0.1}',
                method="POST",
            )
            with _request.urlopen(req, timeout=60) as r:
                prof = json.loads(r.read())
            if prof["ok"] is not False:
                raise AssertionError(
                    "debug.profile@0 did not fail the capture — the seam "
                    "no longer fires there"
                )
        finally:
            t.join(timeout=600)
        sc = server2.job_status(c["job_id"])
        if sc["state"] != "done":
            raise AssertionError(
                f"job beside the failed capture: expected done, got "
                f"{sc['state']} ({sc.get('error')})"
            )
        if _digest_workdir(sc["workdir"]) != clean:
            raise AssertionError(
                "debug-soak job artifacts differ from the clean run"
            )
        report["cases"].append(
            {
                "track": "serve",
                "case": "debug_stacks_under_hang_and_profile_fault",
                "schedule": schedule2,
                "stacks_responsive_while_wedged": True,
                "profile_fault_ok_false": True,
                "job": sc["state"],
                "artifacts_identical": True,
            }
        )
        if verbose:
            print(
                f"  ok: serve/debug_stacks_under_hang_and_profile_fault "
                f"({schedule2})"
            )

    def run_serve_job_case() -> None:
        """Job-level failure isolation (the ``serve.job`` seam): the
        armed job fails at execution START — before its run config is
        even built — and goes terminal ``error``.  The SAME request
        resubmitted to the same server completes with artifacts
        byte-identical to the serve track's clean run: a job-start
        failure burns the job, never the server or the request.  Runs
        after :func:`run_serve_track` (reuses its on-disk stack and
        clean digest)."""
        from land_trendr_tpu.serve import SegmentationServer, ServeConfig

        sdir = str(root / "serve_stack")
        clean = _digest_workdir(str(root / "serve_clean"))
        schedule = "seed=1,serve.job@0"
        # the serve loop drains jobs serially on one thread, so
        # invocation 0 is deterministically the FIRST submission's
        # execution start; max_jobs=2 counts the errored job as one of
        # the two terminal states (max_jobs=1 would shut down on it)
        server = SegmentationServer(
            ServeConfig(
                workdir=str(root / "serve_jobfault"),
                max_jobs=2,
                feed_cache_mb=64,
                fault_schedule=schedule,
            )
        )
        job = {
            "stack_dir": sdir,
            "tile_size": base_kw["tile_size"],
            "params": {"max_segments": 4, "vertex_count_overshoot": 2},
            "max_retries": retries,
            "run_overrides": {"retry_backoff_s": 0.0},
        }
        a = server.submit(dict(job))
        b = server.submit(dict(job))
        server.serve_forever()
        sa = server.job_status(a["job_id"])
        sb = server.job_status(b["job_id"])
        if sa["state"] != "error":
            raise AssertionError(
                f"serve.job@0: expected the first job terminal 'error', "
                f"got {sa['state']} ({sa.get('error')})"
            )
        if sb["state"] != "done":
            raise AssertionError(
                f"serve.job resubmit: expected done, got {sb['state']} "
                f"({sb.get('error')})"
            )
        if _digest_workdir(sb["workdir"]) != clean:
            raise AssertionError(
                "serve.job resubmit artifacts differ from the clean run"
            )
        report["cases"].append({
            "track": "serve",
            "case": "job_fault_then_resubmit",
            "schedule": schedule,
            "job_a": sa["state"],
            "job_b": sb["state"],
            "artifacts_identical": True,
        })
        if verbose:
            print(f"  ok: serve/job_fault_then_resubmit ({schedule})")

    def run_batch_track() -> None:
        """Cross-job batching failure semantics (ISSUE 18): with the
        server's armed plan firing at ``batch.pack`` (the first
        candidate is EXCLUDED from the batch — it runs solo in its
        normal queue turn) and at ``batch.demux`` (one member stops
        receiving demuxed tiles at tile 0 and its own run recomputes
        them), every job of a 3-job same-shape flood still completes
        with artifacts byte-identical to the clean run, and the batch
        events on the stream stay schema- and value-lint clean — a
        batching fault degrades packing, never correctness."""
        from land_trendr_tpu.obs.events import validate_events_file
        from land_trendr_tpu.serve import SegmentationServer, ServeConfig

        sys.path.insert(0, str(REPO / "tools"))
        from check_events_schema import value_lints

        sdir = str(root / "serve_stack")  # the serve track wrote it
        clean = _digest_workdir(str(root / "serve_clean"))
        schedule = "seed=3,batch.pack@0=io,batch.demux@0=io"
        srv_wd = str(root / "serve_batch")
        server = SegmentationServer(
            ServeConfig(
                workdir=srv_wd,
                max_jobs=3,
                feed_cache_mb=64,
                batch=True,
                batch_window_ms=150.0,
                fault_schedule=schedule,
            )
        )
        job = {
            "stack_dir": sdir,
            "tile_size": base_kw["tile_size"],
            "params": {"max_segments": 4, "vertex_count_overshoot": 2},
            "max_retries": retries,
            "run_overrides": {"retry_backoff_s": 0.0},
        }
        subs = [server.submit(dict(job)) for _ in range(3)]
        server.serve_forever()  # drains all three jobs, then shuts down
        for snap in subs:
            s = server.job_status(snap["job_id"])
            if s["state"] != "done":
                raise AssertionError(
                    f"batch track: job {snap['job_id']} ended "
                    f"{s['state']} ({s.get('error')})"
                )
            if _digest_workdir(s["workdir"]) != clean:
                raise AssertionError(
                    f"batch track: job {snap['job_id']} artifacts differ "
                    "from the clean run"
                )
        evs = [
            json.loads(line) for line in
            (Path(srv_wd) / "events.jsonl").read_text().splitlines()
            if line.strip()
        ]
        launches = [e for e in evs if e["ev"] == "batch_launch"]
        demuxes = [e for e in evs if e["ev"] == "batch_demux"]
        if not launches:
            raise AssertionError(
                "batch track: no batch_launch — the window never "
                "coalesced the queued siblings"
            )
        # pack@0 fires on the FIRST candidate of the first collect: the
        # first launch coalesces leader + ONE member, not both
        if launches[0]["jobs"] != 2:
            raise AssertionError(
                f"batch.pack@0 should have excluded one candidate from "
                f"the first launch, got jobs={launches[0]['jobs']}"
            )
        # demux@0 fires on the first demuxed tile: that member's demux
        # stops at 0 tiles (its own run recomputes); a later batch must
        # still demux normally somewhere on the stream
        if not any(d["tiles"] == 0 for d in demuxes):
            raise AssertionError(
                "batch.demux@0 never stopped a member's demux at tile 0: "
                f"{demuxes}"
            )
        if not any(d["tiles"] > 0 for d in demuxes):
            raise AssertionError(
                "no member ever received demuxed tiles — batching is "
                f"not actually demuxing: {demuxes}"
            )
        lint = validate_events_file(
            str(Path(srv_wd) / "events.jsonl"), extra=value_lints()
        )
        if lint:
            raise AssertionError(
                f"batch track: server stream lint-dirty: {lint[:3]}"
            )
        report["cases"].append({
            "track": "serve",
            "case": "batch_pack_and_demux_faults",
            "schedule": schedule,
            "launches": len(launches),
            "first_launch_jobs": launches[0]["jobs"],
            "demux_tiles": [d["tiles"] for d in demuxes],
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: serve/batch_pack_and_demux_faults ({schedule}; "
                f"{len(launches)} launch(es), demux tiles "
                f"{[d['tiles'] for d in demuxes]})"
            )

    def run_batch_kill_case() -> None:
        """Full mode: a batching server SIGKILLed MID-BATCH — leader
        still computing, members already holding demuxed tiles.  Each
        job's pinned workdir then resumes independently (the stock
        per-job resume — no batch machinery in the recovery path),
        skipping exactly its durable tiles, and finishes byte-identical
        to the clean run.  Full mode only: a cold jax subprocess costs
        tens of seconds the smoke budget does not have (the smoke's
        batch track drives the same isolation seams deterministically).
        """
        import os as _os
        import signal as _signal
        import subprocess as _subprocess

        from land_trendr_tpu.ops.indices import required_bands
        from land_trendr_tpu.runtime import load_stack_dir

        sdir = str(root / "serve_stack")
        clean = _digest_workdir(str(root / "serve_clean"))
        n_tiles = len(clean)
        wds = [str(root / f"batch_kill_job{i}") for i in range(3)]
        payloads = [
            {
                "stack_dir": sdir,
                "tile_size": base_kw["tile_size"],
                "params": {"max_segments": 4, "vertex_count_overshoot": 2},
                "max_retries": retries,
                "workdir": wd,
                "out_dir": wd + "_o",
                "run_overrides": {"retry_backoff_s": 0.0},
            }
            for wd in wds
        ]
        cfg_path = root / "batch_kill_jobs.json"
        cfg_path.write_text(json.dumps(payloads))
        script = root / "batch_kill_server.py"
        # every dispatch paced slow so the kill lands with the leader
        # mid-scene and members partially demuxed
        script.write_text(
            "import json, sys\n"
            f"sys.path.insert(0, {str(REPO)!r})\n"
            "from land_trendr_tpu.serve import SegmentationServer, "
            "ServeConfig\n"
            "server = SegmentationServer(ServeConfig(\n"
            f"    workdir={str(root / 'batch_kill_srv')!r}, max_jobs=3,\n"
            "    feed_cache_mb=64, batch=True, batch_window_ms=300.0,\n"
            "    fault_schedule='seed=5,dispatch%1.0=slow:0.3',\n"
            "))\n"
            "for p in json.load(open(sys.argv[1])):\n"
            "    server.submit(p)\n"
            "server.serve_forever()\n"
        )
        proc = _subprocess.Popen(
            [sys.executable, str(script), str(cfg_path)],
            stdout=_subprocess.PIPE, stderr=_subprocess.PIPE, text=True,
        )
        deadline = time.monotonic() + 300
        killed = False
        try:
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    _, err = proc.communicate()
                    raise AssertionError(
                        "batch-kill server exited before the kill:\n"
                        + err[-4000:]
                    )
                lead = len(list(Path(wds[0]).glob("tile_*.npz")))
                mem = max(
                    len(list(Path(w).glob("tile_*.npz"))) for w in wds[1:]
                )
                if mem >= 1 and lead < n_tiles:
                    _os.kill(proc.pid, _signal.SIGKILL)
                    killed = True
                    break
                time.sleep(0.05)
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.communicate()
        if not killed:
            raise AssertionError(
                "batch-kill: the mid-batch window never opened — no "
                "member held a demuxed tile while the leader was short"
            )
        pre = [len(list(Path(w).glob("tile_*.npz"))) for w in wds]
        stack = load_stack_dir(sdir, bands=required_bands("nbr", ()))
        for wd, durable in zip(wds, pre):
            summary = _run(
                stack,
                RunConfig(workdir=wd, out_dir=wd + "_o", **base_kw),
            )
            # a manifest-readable pre-kill tile must resume, not
            # recompute — the demuxed artifacts ARE the durable state
            if summary["tiles_skipped_resume"] < max(durable - 1, 0):
                raise AssertionError(
                    f"batch-kill: {wd} resumed only "
                    f"{summary['tiles_skipped_resume']} of {durable} "
                    "durable tile(s) — demuxed artifacts did not resume"
                )
            if _digest_workdir(wd) != clean:
                raise AssertionError(
                    f"batch-kill: {wd} artifacts differ from the clean "
                    "run after resume"
                )
        report["cases"].append({
            "track": "serve",
            "case": "batch_sigkill_mid_batch_resume",
            "schedule": "SIGKILL server mid-batch",
            "tiles_durable_before_kill": pre,
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: serve/batch_sigkill_mid_batch_resume "
                f"({pre} tile(s) durable pre-kill)"
            )

    def run_router_track() -> None:
        """Fleet-router failure semantics (ISSUE 13), in-process: one
        real replica (a SegmentationServer on a thread) behind a
        :class:`~land_trendr_tpu.fleet.router.FleetRouter` whose armed
        plan fires at the two router seams.

        * ``router.forward@0=io``: the FIRST forward fails — the job
          re-enters the router queue and routes again (attempt 2), the
          replica lives, artifacts byte-identical to the clean run.
        * ``replica.health@1*6``: six consecutive health probes read as
          failed — the replica is marked unready (``replica_down``
          reason="health") WITHOUT failing the accepted job, which
          keeps polling, completes byte-identically, and the replica
          recovers (``replica_up``) once the probes clear.
        """
        import threading as _threading

        from land_trendr_tpu.fleet import FleetRouter, RouterConfig
        from land_trendr_tpu.serve import SegmentationServer, ServeConfig

        sdir = str(root / "serve_stack")  # the serve track wrote it
        clean = _digest_workdir(str(root / "serve_clean"))
        job = {
            "stack_dir": sdir,
            "tile_size": base_kw["tile_size"],
            "params": {"max_segments": 4, "vertex_count_overshoot": 2},
            "max_retries": retries,
            "run_overrides": {"retry_backoff_s": 0.0},
        }
        server = SegmentationServer(
            ServeConfig(workdir=str(root / "router_replica"),
                        feed_cache_mb=64)
        )
        srv_thread = _threading.Thread(target=server.serve_forever)
        srv_thread.start()
        try:
            for case_name, schedule in (
                ("forward_fault_rerouted", "seed=1,router.forward@0=io"),
                # the plan is process-global, so the slow dispatch paces
                # the IN-PROCESS replica's job long enough that three
                # health beats (0.2s apart) fail while it runs —
                # invocation 0 is the adopt-time probe and must succeed
                ("health_fault_unready_job_survives",
                 "seed=2,replica.health@1*8,dispatch%1.0=slow:0.3"),
            ):
                rt_dir = str(root / f"router_{case_name}")
                router = FleetRouter(RouterConfig(
                    workdir=rt_dir,
                    replicas=(f"http://127.0.0.1:{server.port}",),
                    health_interval_s=0.2,
                    route_retries=2,
                    fault_schedule=schedule,
                ))
                rt_thread = _threading.Thread(target=router.serve_forever)
                rt_thread.start()
                try:
                    snap = router.submit(dict(job))
                    deadline = time.monotonic() + 300
                    while time.monotonic() < deadline:
                        s = router.job_status(snap["job_id"])
                        if s["state"] not in ("queued", "routed"):
                            break
                        time.sleep(0.1)
                    if case_name.startswith("health"):
                        # the scheduled probe faults are exhausted; wait
                        # out the recovery probe so the replica_up
                        # assertion is not a race against stop()
                        while time.monotonic() < deadline:
                            pool = router.stats()["replicas"]
                            if pool and pool[0]["state"] == "ready":
                                break
                            time.sleep(0.1)
                finally:
                    router.stop()
                    rt_thread.join(timeout=300)
                if s["state"] != "done":
                    raise AssertionError(
                        f"router/{case_name}: job ended {s['state']} "
                        f"({s.get('error')})"
                    )
                if _digest_workdir(s["workdir"]) != clean:
                    raise AssertionError(
                        f"router/{case_name}: artifacts differ from the "
                        "clean run"
                    )
                evs = [
                    json.loads(line) for line in
                    (Path(rt_dir) / "events.jsonl").read_text().splitlines()
                ]
                kinds = [e["ev"] for e in evs]
                if case_name == "forward_fault_rerouted":
                    if s["attempts"] != 2:
                        raise AssertionError(
                            f"router/forward: expected 2 route attempts "
                            f"(fault then re-route), got {s['attempts']}"
                        )
                    # route_decision marks the SUCCESSFUL forward; the
                    # faulted first try leaves only the attempt counter
                    decisions = [
                        e for e in evs if e["ev"] == "route_decision"
                    ]
                    if len(decisions) != 1 or decisions[0]["attempt"] != 2:
                        raise AssertionError(
                            "router/forward: expected exactly the "
                            f"attempt-2 route_decision, got {decisions}"
                        )
                    # request tracing (ISSUE 15): the re-routed request
                    # assembles as ONE trace with BOTH forward hops —
                    # the faulted first try ok=false, the re-route
                    # ok=true — and a blame split summing to the
                    # router-observed latency
                    _assert_two_hop_trace(
                        s["trace_id"],
                        [rt_dir, str(root / "router_replica"),
                         s["workdir"]],
                        expect_failed_first=True,
                    )
                else:
                    downs = [
                        e for e in evs if e["ev"] == "replica_down"
                    ]
                    if not downs or downs[0]["reason"] != "health":
                        raise AssertionError(
                            "router/health: the probe faults never "
                            f"marked the replica unready ({downs})"
                        )
                    if kinds.count("replica_up") < 2:
                        raise AssertionError(
                            "router/health: the replica never recovered "
                            "after the probes cleared"
                        )
                report["cases"].append({
                    "track": "router",
                    "case": case_name,
                    "schedule": schedule,
                    "job": s["state"],
                    "route_attempts": s["attempts"],
                    "artifacts_identical": True,
                })
                if verbose:
                    print(f"  ok: router/{case_name} ({schedule})")
        finally:
            server.stop()
            srv_thread.join(timeout=120)

    def run_router_kill_case() -> None:
        """Full mode: a SPAWNED replica SIGKILLed mid-job.  The router
        detects the dead process, re-routes the job (its router-pinned
        workdir resumes on the survivor), and the job completes with
        artifacts byte-identical to the clean run — zero accepted jobs
        lost to the kill.  Full mode only: two cold jax replica
        processes cost tens of seconds the smoke budget does not have
        (the smoke's router.forward case drives the same re-route code
        path deterministically)."""
        import os as _os
        import signal as _signal
        import threading as _threading

        from land_trendr_tpu.fleet import FleetRouter, RouterConfig

        sdir = str(root / "serve_stack")
        clean = _digest_workdir(str(root / "serve_clean"))
        rt_dir = str(root / "router_kill")
        router = FleetRouter(RouterConfig(
            workdir=rt_dir,
            spawn_replicas=2,
            health_interval_s=0.3,
            route_retries=3,
            # pace every dispatch so the kill lands mid-job with tiles
            # already durable — the resume-not-recompute proof
            replica_args=(
                "--feed-cache-mb", "64",
                "--fault-schedule", "seed=5,dispatch%1.0=slow:0.3",
            ),
        ))
        rt_thread = _threading.Thread(target=router.serve_forever)
        rt_thread.start()
        try:
            snap = router.submit({
                "stack_dir": sdir,
                "tile_size": base_kw["tile_size"],
                "params": {"max_segments": 4, "vertex_count_overshoot": 2},
                "run_overrides": {"retry_backoff_s": 0.0},
            })
            wd = Path(snap["workdir"])
            deadline = time.monotonic() + 300
            victim = None
            while time.monotonic() < deadline and victim is None:
                with router._lock:
                    for r in router.pool:
                        if r.inflight and r.proc is not None \
                                and r.proc.poll() is None:
                            victim = r
                if victim is None:
                    time.sleep(0.05)
                elif not list(wd.glob("tile_*.npz")):
                    victim = None  # kill only once work is durable
                    time.sleep(0.05)
            if victim is None:
                raise AssertionError(
                    "router kill: no replica ever held the job"
                )
            pre_kill = len(list(wd.glob("tile_*.npz")))
            _os.kill(victim.proc.pid, _signal.SIGKILL)
            while time.monotonic() < deadline:
                s = router.job_status(snap["job_id"])
                if s["state"] not in ("queued", "routed"):
                    break
                time.sleep(0.1)
        finally:
            router.stop()
            rt_thread.join(timeout=600)
        if s["state"] != "done":
            raise AssertionError(
                f"router kill: job ended {s['state']} ({s.get('error')})"
            )
        if s["attempts"] < 2:
            raise AssertionError(
                "router kill: the job was never re-routed — the kill "
                "missed its window"
            )
        if _digest_workdir(str(wd)) != clean:
            raise AssertionError(
                "router kill: artifacts differ from the clean run"
            )
        # request tracing (ISSUE 15): the SIGKILLed job assembles as
        # ONE trace — both forward hops (the killed replica's and the
        # survivor's, distinct targets) under one trace_id, the
        # re-route attributed in a blame split that sums to the
        # router-observed latency; artifacts above stayed byte-identical
        trace = _assert_two_hop_trace(
            s["trace_id"], [rt_dir], expect_failed_first=False,
        )
        report["cases"].append({
            "track": "router",
            "case": "replica_sigkill_rerouted",
            "schedule": "SIGKILL replica mid-job",
            "tiles_durable_before_kill": pre_kill,
            "route_attempts": s["attempts"],
            "artifacts_identical": True,
            "trace_id": s["trace_id"],
            "trace_hops": [h["replica"] for h in trace["hops"]],
            "trace_blame": trace["blame"],
        })
        if verbose:
            print(
                f"  ok: router/replica_sigkill_rerouted "
                f"({pre_kill} tile(s) durable pre-kill, "
                f"{s['attempts']} route attempts)"
            )

    def run_journal_track() -> None:
        """Crash-safe control plane (ISSUE 20), in-process: the two
        journal seams against one real replica.

        * ``router.journal@0=io``: the FIRST admission's journal append
          fails — the submission is refused 503 ``journal_error`` (a
          job the journal cannot make durable is never accepted), the
          router lives, and the resubmission completes byte-identically.
        * ``router.recover@0=io``: a fabricated crash journal (admitted
          + forwarded to a dead replica base) replays at startup; the
          armed recovery-probe fault degrades the job to the requeue
          path — it re-routes to the live replica, resumes, and
          finishes byte-identically with ONE complete trace under the
          preserved trace id.  An idempotent resubmission after the
          restart dedupes onto the recovered job.
        """
        import threading as _threading

        from land_trendr_tpu.fleet import FleetRouter, RouterConfig
        from land_trendr_tpu.obs.reqtrace import assemble_request
        from land_trendr_tpu.serve import SegmentationServer, ServeConfig
        from land_trendr_tpu.serve.server import Rejection
        from tools.lt_request import expand_paths

        sdir = str(root / "serve_stack")
        clean = _digest_workdir(str(root / "serve_clean"))
        job = {
            "stack_dir": sdir,
            "tile_size": base_kw["tile_size"],
            "params": {"max_segments": 4, "vertex_count_overshoot": 2},
            "max_retries": retries,
            "run_overrides": {"retry_backoff_s": 0.0},
        }
        server = SegmentationServer(
            ServeConfig(workdir=str(root / "journal_replica"),
                        feed_cache_mb=64)
        )
        srv_thread = _threading.Thread(target=server.serve_forever)
        srv_thread.start()
        try:
            # -- case 1: append fault → 503, resubmission lands --------
            case_name = "journal_fault_503_then_resubmit"
            schedule = "seed=1,router.journal@0=io"
            rt_dir = str(root / "router_journal_fault")
            router = FleetRouter(RouterConfig(
                workdir=rt_dir,
                replicas=(f"http://127.0.0.1:{server.port}",),
                health_interval_s=0.2,
                fault_schedule=schedule,
            ))
            rt_thread = _threading.Thread(target=router.serve_forever)
            rt_thread.start()
            try:
                try:
                    router.submit(dict(job))
                    raise AssertionError(
                        "journal fault: the un-durable submission was "
                        "ACCEPTED"
                    )
                except Rejection as e:
                    if e.http_status != 503 or e.reason != "journal_error":
                        raise AssertionError(
                            f"journal fault: expected 503 journal_error, "
                            f"got {e.http_status} {e.reason}"
                        )
                snap = router.submit(dict(job))
                deadline = time.monotonic() + 300
                while time.monotonic() < deadline:
                    s = router.job_status(snap["job_id"])
                    if s["state"] not in ("queued", "routed"):
                        break
                    time.sleep(0.1)
            finally:
                router.stop()
                rt_thread.join(timeout=300)
            if s["state"] != "done":
                raise AssertionError(
                    f"journal fault: resubmitted job ended {s['state']} "
                    f"({s.get('error')})"
                )
            if _digest_workdir(s["workdir"]) != clean:
                raise AssertionError(
                    "journal fault: artifacts differ from the clean run"
                )
            evs = [
                json.loads(line) for line in
                (Path(rt_dir) / "events.jsonl").read_text().splitlines()
            ]
            rejects = [
                e for e in evs
                if e["ev"] == "job_rejected"
                and e.get("reason") == "journal_error"
            ]
            if len(rejects) != 1:
                raise AssertionError(
                    f"journal fault: expected one journal_error "
                    f"rejection event, got {rejects}"
                )
            appended = [e for e in evs if e["ev"] == "journal_append"]
            kinds = sorted({e["rec"] for e in appended})
            if kinds != ["admitted", "forwarded", "terminal"]:
                raise AssertionError(
                    f"journal fault: the resubmitted job should journal "
                    f"all three record kinds, got {kinds}"
                )
            report["cases"].append({
                "track": "router",
                "case": case_name,
                "schedule": schedule,
                "job": s["state"],
                "artifacts_identical": True,
            })
            if verbose:
                print(f"  ok: router/{case_name} ({schedule})")

            # -- case 2: crash journal replays; probe fault → requeue --
            case_name = "recover_probe_fault_requeued_resume"
            schedule = "seed=1,router.recover@0=io"
            rt_dir = str(root / "router_recover")
            jid, trace_id = "rt-0-00001", "soakrecover00001"
            jwd = str(root / "router_recover_job")
            payload = dict(job)
            payload["workdir"] = jwd
            payload["out_dir"] = jwd + "_o"
            jdir = Path(rt_dir) / "journal"
            jdir.mkdir(parents=True)
            (jdir / "seg-00000001.jsonl").write_text(
                "\n".join(json.dumps(r) for r in (
                    {
                        "rec": "admitted", "job_id": jid,
                        "payload": payload, "tenant": "soak",
                        "priority": 0, "key": "soak-key",
                        "trace_id": trace_id,
                        "idempotency_key": "soak-recover-1",
                        "workdir": jwd, "out_dir": jwd + "_o",
                        "source": "http", "t": time.time(),
                    },
                    {
                        "rec": "forwarded", "job_id": jid,
                        # a base nothing listens on: the dead incarnation
                        "replica_base": "http://127.0.0.1:9",
                        "replica_job_id": "gone-1", "replica": "r0",
                        "t": time.time(),
                    },
                )) + "\n"
            )
            router = FleetRouter(RouterConfig(
                workdir=rt_dir,
                replicas=(f"http://127.0.0.1:{server.port}",),
                health_interval_s=0.2,
                fault_schedule=schedule,
            ))
            rt_thread = _threading.Thread(target=router.serve_forever)
            rt_thread.start()
            try:
                rec = router.recovery
                if not rec or rec["requeued"] != 1 or rec["replayed"] != 1:
                    raise AssertionError(
                        f"recover: expected the one forwarded job "
                        f"requeued, got {rec}"
                    )
                dedup = router.submit(
                    {**payload, "idempotency_key": "soak-recover-1"}
                )
                if not dedup.get("deduped") or dedup["job_id"] != jid:
                    raise AssertionError(
                        f"recover: idempotent resubmission did not "
                        f"dedupe onto the recovered job: {dedup}"
                    )
                deadline = time.monotonic() + 300
                while time.monotonic() < deadline:
                    s = router.job_status(jid)
                    if s["state"] not in ("queued", "routed"):
                        break
                    time.sleep(0.1)
            finally:
                router.stop()
                rt_thread.join(timeout=300)
            if s["state"] != "done":
                raise AssertionError(
                    f"recover: replayed job ended {s['state']} "
                    f"({s.get('error')})"
                )
            if s["trace_id"] != trace_id:
                raise AssertionError(
                    f"recover: trace id not preserved: {s['trace_id']}"
                )
            if _digest_workdir(jwd) != clean:
                raise AssertionError(
                    "recover: artifacts differ from the clean run"
                )
            evs = [
                json.loads(line) for line in
                (Path(rt_dir) / "events.jsonl").read_text().splitlines()
            ]
            recovered = [e for e in evs if e["ev"] == "router_recovered"]
            if len(recovered) != 1 or recovered[0]["requeued"] != 1:
                raise AssertionError(
                    f"recover: expected one router_recovered with "
                    f"requeued=1, got {recovered}"
                )
            # ONE complete trace under the preserved id, blame summing
            # to the router-observed latency (the PR-15 contract holds
            # across the restart)
            files = expand_paths(
                [rt_dir, str(root / "journal_replica"), jwd]
            )
            tr = assemble_request(files, trace_id)
            if not tr["complete"]:
                raise AssertionError(
                    f"recover: trace {trace_id} did not assemble "
                    f"complete: {tr}"
                )
            if abs(tr["blame_sum_s"] - tr["latency_s"]) > 5e-3:
                raise AssertionError(
                    f"recover: blame {tr['blame']} sums to "
                    f"{tr['blame_sum_s']} vs latency {tr['latency_s']}"
                )
            report["cases"].append({
                "track": "router",
                "case": case_name,
                "schedule": schedule,
                "job": s["state"],
                "recovery": {
                    k: recovered[0].get(k)
                    for k in ("replayed", "requeued", "relayed", "deduped")
                },
                "trace_id": trace_id,
                "artifacts_identical": True,
            })
            if verbose:
                print(f"  ok: router/{case_name} ({schedule})")
        finally:
            server.stop()
            srv_thread.join(timeout=120)

    def run_router_restart_kill_case() -> None:
        """Full mode: the ROUTER process SIGKILLed mid-trace, restarted
        on the same workdir.  The crash-safety contract end to end:
        zero accepted jobs lost (the journal replays the in-flight
        job), the still-running spawned replica is re-adopted (not
        respawned cold), the job completes with artifacts byte-identical
        to the clean run under its preserved trace id, an idempotent
        resubmission dedupes onto it, and a SIGTERM drain leaves the
        clean-shutdown marker.  Full mode only: a cold `lt route`
        process (plus its spawned replica, plus one fresh spawn at
        restart) costs tens of seconds the smoke budget does not have —
        the smoke's journal/recover cases drive the same replay and
        reconcile paths deterministically in-process."""
        import os as _os
        import signal as _signal
        import subprocess as _subprocess
        import sys as _sys
        import urllib.request as _rq

        from land_trendr_tpu.obs.reqtrace import assemble_request
        from tools.lt_request import expand_paths

        def _launch(rt_dir: str) -> "tuple[_subprocess.Popen, int]":
            proc = _subprocess.Popen(
                [
                    _sys.executable, "-m", "land_trendr_tpu", "route",
                    "--workdir", rt_dir,
                    "--route-port", "0",
                    "--spawn-replicas", "1",
                    "--health-interval-s", "0.3",
                    "--replica-args",
                    "--feed-cache-mb 64 "
                    "--fault-schedule seed=5,dispatch%1.0=slow:0.3",
                ],
                stdout=_subprocess.PIPE,
                stderr=_subprocess.DEVNULL,
                text=True,
            )
            line = proc.stdout.readline()
            startup = json.loads(line) if line.strip() else {}
            if not startup.get("routing"):
                proc.kill()
                raise AssertionError(
                    f"router restart: startup line unreadable: {line!r}"
                )
            return proc, int(startup["port"])

        def _http(method: str, url: str, payload=None) -> dict:
            data = (
                json.dumps(payload).encode() if payload is not None
                else None
            )
            req = _rq.Request(url, data=data, method=method)
            req.add_header("Content-Type", "application/json")
            with _rq.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())

        sdir = str(root / "serve_stack")
        clean = _digest_workdir(str(root / "serve_clean"))
        rt_dir = str(root / "router_restart")
        trace_id = "soakrestart00001"
        proc, port = _launch(rt_dir)
        try:
            snap = _http("POST", f"http://127.0.0.1:{port}/jobs", {
                "stack_dir": sdir,
                "tile_size": base_kw["tile_size"],
                "params": {"max_segments": 4, "vertex_count_overshoot": 2},
                "run_overrides": {"retry_backoff_s": 0.0},
                "trace_id": trace_id,
                "idempotency_key": "soak-restart-1",
            })
            jid = snap["job_id"]
            wd = Path(snap["workdir"])
            # kill only once work is durable — the resume-not-recompute
            # proof rides on tiles written before the crash
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline \
                    and not list(wd.glob("tile_*.npz")):
                time.sleep(0.05)
            pre_kill = len(list(wd.glob("tile_*.npz")))
            if not pre_kill:
                raise AssertionError(
                    "router restart: no tile ever became durable"
                )
            _os.kill(proc.pid, _signal.SIGKILL)
            proc.wait(timeout=60)
        except BaseException:
            proc.kill()
            raise
        proc, port = _launch(rt_dir)
        try:
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                s = _http("GET", f"http://127.0.0.1:{port}/jobs/{jid}")
                if s["state"] not in ("queued", "routed"):
                    break
                time.sleep(0.2)
            if s["state"] != "done":
                raise AssertionError(
                    f"router restart: job ended {s['state']} "
                    f"({s.get('error')}) — an accepted job was lost"
                )
            if s["trace_id"] != trace_id:
                raise AssertionError(
                    f"router restart: trace id not preserved: "
                    f"{s['trace_id']}"
                )
            dedup = _http("POST", f"http://127.0.0.1:{port}/jobs", {
                "stack_dir": sdir,
                "tile_size": base_kw["tile_size"],
                "idempotency_key": "soak-restart-1",
            })
            if not dedup.get("deduped") or dedup["job_id"] != jid:
                raise AssertionError(
                    f"router restart: resubmission did not dedupe onto "
                    f"the recovered job: {dedup}"
                )
            health = _http("GET", f"http://127.0.0.1:{port}/healthz")
            rec = health.get("recovery")
            if not rec or rec.get("replayed") != 1:
                raise AssertionError(
                    f"router restart: no recovery summary: {rec}"
                )
        finally:
            # SIGTERM = the documented drain (satellite: `lt route`
            # handles it like Ctrl-C) — the clean marker must follow
            proc.send_signal(_signal.SIGTERM)
            try:
                proc.wait(timeout=120)
            except _subprocess.TimeoutExpired:
                proc.kill()
                raise
        if _digest_workdir(str(wd)) != clean:
            raise AssertionError(
                "router restart: artifacts differ from the clean run"
            )
        if not (Path(rt_dir) / "journal" / "clean").exists():
            raise AssertionError(
                "router restart: SIGTERM drain left no clean-shutdown "
                "marker"
            )
        # the whole journey — pre-kill forward included — is ONE trace
        tr = assemble_request(expand_paths([rt_dir]), trace_id)
        if not tr["complete"]:
            raise AssertionError(
                f"router restart: trace {trace_id} did not assemble "
                f"complete: {tr}"
            )
        if abs(tr["blame_sum_s"] - tr["latency_s"]) > 5e-3:
            raise AssertionError(
                f"router restart: blame {tr['blame']} sums to "
                f"{tr['blame_sum_s']} vs latency {tr['latency_s']}"
            )
        report["cases"].append({
            "track": "router",
            "case": "router_sigkill_restart_recovered",
            "schedule": "SIGKILL router mid-trace, restart same workdir",
            "tiles_durable_before_kill": pre_kill,
            "recovery": rec,
            "trace_id": trace_id,
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: router/router_sigkill_restart_recovered "
                f"({pre_kill} tile(s) durable pre-kill, "
                f"recovery {rec})"
            )

    def run_loadgen_churn_case() -> None:
        """Load-rig churn semantics (the ``loadgen.tick`` seam): a
        seeded closed-loop soak against a 2-replica spawned fleet whose
        churn hook SIGKILLs the busiest replica mid-soak, driven from
        the rig's own scheduler tick.  Zero offered jobs are lost (every
        one reaches ``done``), every pinned trace id still assembles
        through the request-trace store into a sweep point, the job
        artifacts stay byte-identical to the clean run, AND the leg's
        recorded decision log replays byte-identically through the
        offline simulator — churn must not cost correctness on any of
        the three planes.  Full mode only: three cold jax replica
        processes (two spawned + the respawn-free survivor path) cost
        tens of seconds the smoke budget does not have."""
        import signal as _signal
        import threading as _threading

        from land_trendr_tpu.fleet import FleetRouter, RouterConfig
        from land_trendr_tpu.fleet.capacity import (
            assemble_sweep,
            percentile,
            replay_decisions,
        )
        from land_trendr_tpu.loadgen import (
            InProcClient,
            LoadConfig,
            LoadRunner,
        )
        from land_trendr_tpu.obs.events import validate_events_file
        from land_trendr_tpu.runtime import faults

        sys.path.insert(0, str(REPO / "tools"))
        from check_events_schema import value_lints

        sdir = str(root / "serve_stack")
        clean = _digest_workdir(str(root / "serve_clean"))
        rt_dir = str(root / "router_loadgen_churn")
        router = FleetRouter(RouterConfig(
            workdir=rt_dir,
            spawn_replicas=2,
            health_interval_s=0.3,
            route_retries=3,
            decision_log=True,
            # pace every dispatch so the kill lands mid-job
            replica_args=(
                "--feed-cache-mb", "64",
                "--fault-schedule", "seed=5,dispatch%1.0=slow:0.3",
            ),
        ))
        rt_thread = _threading.Thread(target=router.serve_forever)
        rt_thread.start()
        killed: list = []

        def churn() -> None:
            # first firing tick with a busy live replica: SIGKILL it
            if killed:
                return
            with router._lock:
                for r in router.pool:
                    if r.inflight and r.proc is not None \
                            and r.proc.poll() is None:
                        r.proc.send_signal(_signal.SIGKILL)
                        killed.append(r.rid)
                        return

        def payload_fn(req) -> dict:
            # one shape for every request: the soak's identity check is
            # against ONE clean digest, so params must not vary
            return {
                "stack_dir": sdir,
                "tile_size": base_kw["tile_size"],
                "params": {"max_segments": 4, "vertex_count_overshoot": 2},
                "trace_id": req.trace_id,
                "run_overrides": {"retry_backoff_s": 0.0},
            }

        plan = faults.activate(
            faults.parse_schedule("seed=9,loadgen.tick%1.0")
        )
        try:
            runner = LoadRunner(
                LoadConfig(
                    mode="closed", duration_s=120.0, requests=6,
                    workers=2, seed=11, tenants=2, timeout_s=240.0,
                ),
                InProcClient(router), payload_fn,
                telemetry=router.telemetry, churn=churn,
            )
            rep = runner.run(phase="fault_soak")
            # assemble + emit the sweep point while the router's
            # telemetry scope is still open
            sweep = assemble_sweep(rt_dir, rep.trace_ids)
            if router.telemetry is not None:
                router.telemetry.sweep_point(
                    replicas=2, offered_qps=rep.offered / max(rep.wall_s, 1e-6),
                    achieved_qps=rep.done / max(rep.wall_s, 1e-6),
                    p50_s=percentile(sweep["latencies"], 50.0),
                    p99_s=percentile(sweep["latencies"], 99.0),
                    goodput_qps=rep.done / max(rep.wall_s, 1e-6),
                    done=rep.done, failed=rep.failed,
                    rejected=rep.rejected, assembled=sweep["assembled"],
                    window_s=rep.wall_s,
                )
        finally:
            faults.deactivate()
            router.stop()
            rt_thread.join(timeout=600)
        if not killed:
            raise AssertionError(
                "loadgen churn: the tick seam never found a busy "
                "replica to kill"
            )
        if rep.churned < 1:
            raise AssertionError(
                "loadgen churn: the loadgen.tick seam never fired"
            )
        if not (rep.offered == rep.done == 6
                and rep.failed == 0 and rep.rejected == 0):
            raise AssertionError(
                f"loadgen churn: lost jobs — offered {rep.offered}, "
                f"done {rep.done}, failed {rep.failed}, rejected "
                f"{rep.rejected} ({[o for o in rep.outcomes if o.outcome != 'done']})"
            )
        if sweep["assembled"] != 6 or len(sweep["latencies"]) != 6:
            raise AssertionError(
                f"loadgen churn: sweep point incomplete after the kill "
                f"— {sweep['assembled']} assembled, "
                f"{len(sweep['latencies'])} latencies of 6"
            )
        # the kill is VISIBLE in the trace store: at least one request
        # re-routed (two forward hops)
        evs = [
            json.loads(line) for line in
            (Path(rt_dir) / "events.jsonl").read_text().splitlines()
        ]
        rerouted = [
            e for e in evs
            if e["ev"] == "route_decision" and e.get("attempt", 1) >= 2
        ]
        if not rerouted:
            raise AssertionError(
                "loadgen churn: no re-routed job — the SIGKILL missed "
                "every inflight window"
            )
        for jwd in sorted(Path(rt_dir).glob("jobs/*/work")):
            if _digest_workdir(str(jwd)) != clean:
                raise AssertionError(
                    f"loadgen churn: {jwd} artifacts differ from the "
                    "clean run"
                )
        lint = validate_events_file(
            str(Path(rt_dir) / "events.jsonl"), extra=value_lints()
        )
        if lint:
            raise AssertionError(
                f"loadgen churn: router stream lint-dirty: {lint[:3]}"
            )
        replay = replay_decisions(str(Path(rt_dir) / "decisions.jsonl"))
        if not replay.match:
            raise AssertionError(
                f"loadgen churn: decision replay diverged at seq "
                f"{replay.mismatch_seq}: {replay.mismatch}"
            )
        report["cases"].append({
            "track": "router",
            "case": "loadgen_tick_churn_sigkill",
            "schedule": "seed=9,loadgen.tick%1.0",
            "killed_replica": killed[0],
            "churn_ticks": rep.churned,
            "rerouted_jobs": len(rerouted),
            "done": rep.done,
            "sweep_assembled": sweep["assembled"],
            "artifacts_identical": True,
            "replay_decisions": replay.decisions,
            "replay_match": True,
        })
        if verbose:
            print(
                f"  ok: router/loadgen_tick_churn_sigkill "
                f"(killed {killed[0]}, {len(rerouted)} re-route(s), "
                f"{replay.decisions} decisions replayed)"
            )

    def run_lease_kill_case() -> None:
        """Elastic failure semantics (ISSUE 12): two INDEPENDENT worker
        processes share one workdir through the shared-manifest lease
        queue alone; the victim — slow, holding leases — is SIGKILLed
        mid-run.  The survivor steals the expired leases and finishes
        the whole scene WITHOUT a resume, artifacts byte-identical to
        the clean run.  Full mode only: two cold jax processes cost
        tens of seconds the tier-1 smoke budget does not have (the
        smoke's lease_acquire case + tests/test_leases.py cover the
        in-process lease paths).  The worker spawn / config / manifest
        audit reuse ``tools/elastic_soak.py``'s helpers — one copy of
        the worker contract."""
        import os
        import signal

        from tools.elastic_soak import (
            _manifest_records,
            _spawn_worker,
            _write_worker_cfg,
        )

        wd = str(root / "eager_lease_kill")

        def cfg_file(name: str, run_kw: dict) -> str:
            # the eager track's 48×40 scene, so the clean digest is shared
            return _write_worker_cfg(
                root / name, wd, 48, 20,
                {
                    "params": {
                        "max_segments": 4, "vertex_count_overshoot": 2,
                    },
                    **run_kw,
                },
                height=40,
            )

        lease_kw = {"lease_batch": 2, "lease_ttl_s": 1.0}
        a = _spawn_worker(cfg_file("lease_kill_a.json", {
            **lease_kw,
            # slow per tile: the victim is guaranteed mid-run, leases in
            # hand, when the kill lands
            "fault_schedule": "seed=5,compute.wait%1.0=slow:0.3",
        }))
        b = _spawn_worker(cfg_file("lease_kill_b.json", dict(lease_kw)))

        def recs() -> list:
            try:
                return _manifest_records(wd)
            except OSError:
                return []

        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if a.poll() is not None:
                raise AssertionError(
                    "lease-kill victim exited before the kill: "
                    + a.stderr.read()[-2000:]
                )
            rs = recs()
            holds = any(
                r.get("kind") == "lease"
                and isinstance(r.get("owner"), str)
                and f":{a.pid}:" in r["owner"]
                for r in rs
            )
            if holds and sum(1 for r in rs if r.get("kind") == "tile") >= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("lease-kill victim never held a lease")
        os.kill(a.pid, signal.SIGKILL)
        a.communicate()
        _, err_b = b.communicate(timeout=600)
        if b.returncode != 0:
            raise AssertionError(
                f"lease-kill survivor failed:\n{err_b[-4000:]}"
            )
        got = _digest_workdir(wd)
        clean = _digest_workdir(str(root / "eager_clean"))
        if got != clean:
            raise AssertionError(
                "lease-kill artifacts differ from the clean run"
            )
        steals = [
            r for r in recs()
            if r.get("kind") == "lease" and r.get("mode") == "steal"
        ]
        if not steals:
            raise AssertionError(
                "survivor never stole the dead victim's leases — the run "
                "completing means the TTL/steal path silently changed"
            )
        report["cases"].append({
            "track": "eager",
            "case": "lease_kill_steal",
            "schedule": "SIGKILL victim mid-lease",
            "steals": len(steals),
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: eager/lease_kill_steal ({len(steals)} steal "
                "claim(s) after SIGKILL)"
            )

    def run_tune_case(stack) -> None:
        """Autotuner probe-failure semantics (the ``tune.probe`` seam):
        an injected probe failure skips THAT knob group — its knobs fall
        back to defaults and the ``tune_probe`` event carries
        ``ok=false`` — while the other groups probe normally, the stream
        stays schema-clean, and a subsequent run resolving its "auto"
        knobs through the resulting profile completes with artifacts
        byte-identical to the clean run (a failed probe degrades tuning,
        never correctness)."""
        from land_trendr_tpu.obs import Telemetry
        from land_trendr_tpu.obs.events import iter_events, validate_events_file
        from land_trendr_tpu.runtime import faults
        from land_trendr_tpu.tune import KNOB_DEFAULTS, autotune
        from land_trendr_tpu.tune.probes import PROBE_GROUPS

        sys.path.insert(0, str(REPO / "tools"))
        from check_events_schema import value_lints

        store_dir = str(root / "tune_store")
        tw = str(root / "tune_events")
        plan = faults.activate(faults.parse_schedule("seed=1,tune.probe@0"))
        try:
            telemetry = Telemetry(tw, fingerprint="tune")
            try:
                telemetry.run_start(
                    fingerprint="tune", process_index=0, process_count=1,
                    tiles_total=0, tiles_todo=0, tiles_skipped_resume=0,
                    mesh_devices=1, impl="tune",
                )
                h, w = stack.shape
                profile = autotune(
                    store_dir, height=h, width=w, n_years=stack.n_years,
                    smoke=True, reps=1, telemetry=telemetry,
                )
                telemetry.run_done(
                    "ok", tiles_done=0, pixels=0, wall_s=0.0,
                    px_per_s=0.0, fit_rate=0.0,
                )
            finally:
                telemetry.close()
        finally:
            faults.deactivate()
        if [s for s, _i, _k in plan.injected()] != ["tune.probe"]:
            raise AssertionError(
                f"tune.probe seam did not fire exactly once: {plan.injected()}"
            )
        skipped = [g for g, r in profile["groups"].items() if not r["ok"]]
        if len(skipped) != 1:
            raise AssertionError(
                f"expected exactly one skipped group, got {skipped}"
            )
        for knob in PROBE_GROUPS[skipped[0]][1]:
            if profile["knobs"][knob] != KNOB_DEFAULTS[knob]:
                raise AssertionError(
                    f"skipped group {skipped[0]}: knob {knob} drifted off "
                    f"its default ({profile['knobs'][knob]})"
                )
        events = list(iter_events(str(Path(tw) / "events.jsonl")))
        failed_probes = [
            r for r in events if r["ev"] == "tune_probe" and r["ok"] is False
        ]
        if len(failed_probes) != 1 or failed_probes[0]["group"] != skipped[0]:
            raise AssertionError(
                f"expected one tune_probe ok=false for {skipped[0]}, got "
                f"{failed_probes}"
            )
        lint = validate_events_file(
            str(Path(tw) / "events.jsonl"), extra=value_lints()
        )
        if lint:
            raise AssertionError(f"tune event stream lint-dirty: {lint[:3]}")
        # the run behind the degraded profile: "auto" execution knobs
        # resolve through it; artifacts must match the clean run exactly
        wd = str(root / "eager_tune")
        cfg = RunConfig(
            workdir=wd,
            out_dir=wd + "_o",
            feed_workers="auto",
            decode_workers="auto",
            feed_cache_mb="auto",
            fetch_depth="auto",
            upload_depth="auto",
            tune_store_dir=store_dir,
            **base_kw,
        )
        summary = _run(stack, cfg)
        if summary.get("tune", {}).get("source") != "store":
            raise AssertionError(
                f"auto knobs did not resolve from the store: "
                f"{summary.get('tune')}"
            )
        got = _digest_workdir(wd)
        clean = _digest_workdir(str(root / "eager_clean"))
        if got != clean:
            raise AssertionError(
                "tuned-profile run artifacts differ from the clean run"
            )
        report["cases"].append({
            "track": "eager",
            "case": "tune_probe_fault",
            "schedule": "seed=1,tune.probe@0",
            "skipped_group": skipped[0],
            "artifacts_identical": True,
        })
        if verbose:
            print(
                f"  ok: eager/tune_probe_fault (group {skipped[0]} skipped, "
                "run byte-identical)"
            )

    eager = _make_eager(40, 48)
    run_track("eager", eager, _eager_cases(retries), tile_size=20)
    run_straggler_case(eager)
    run_lease_steal_case(eager)
    run_merge_peer_case()
    run_tune_case(eager)
    run_fleet_case(eager)
    if not smoke:
        run_lease_kill_case()
    run_serve_track()
    run_serve_job_case()
    run_batch_track()
    run_router_track()
    run_journal_track()
    if not smoke:
        run_batch_kill_case()
        run_router_kill_case()
        run_router_restart_kill_case()
        run_loadgen_churn_case()
    lazy = _make_lazy(str(root / "c2"), 96)
    # lazy windows revisit strips across tiles: give the decode seams a
    # real cache to poison (cases that pin their own feed_cache_mb —
    # the store seam needs the RAM tier OFF — keep it)
    lazy_cases = [
        dataclasses.replace(c, cfg_kw={"feed_cache_mb": 64, **c.cfg_kw})
        for c in _LAZY_CASES
    ]
    run_track("lazy", lazy, lazy_cases, tile_size=48)

    if not smoke:
        # probabilistic rounds: every seed a different deterministic storm
        # across the raising driver seams, still byte-identical
        for seed in range(seeds):
            wd = str(root / f"storm_{seed}")
            sched = (
                f"seed={seed},dispatch%0.1,compute.wait%0.1,"
                "fetch.wait%0.1=io,feed%0.05=io"
            )
            cfg = RunConfig(
                workdir=wd,
                out_dir=wd + "_o",
                fault_schedule=sched,
                fetch_packed=True,
                max_retries=6,
                **{k: v for k, v in base_kw.items() if k != "max_retries"},
            )
            summary = _run(eager, cfg)
            got = _digest_workdir(wd)
            clean = _digest_workdir(str(root / "eager_clean"))
            if got != clean:
                raise AssertionError(f"storm seed={seed}: artifacts differ")
            report["cases"].append(
                {
                    "track": "storm",
                    "case": f"seed={seed}",
                    "schedule": sched,
                    "faults_fired": len(summary.get("faults_injected", [])),
                    "artifacts_identical": True,
                }
            )
            if verbose:
                print(f"  ok: storm/seed={seed}")

    report["ok"] = True
    if keep is None:
        shutil.rmtree(root, ignore_errors=True)
    return report


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale tier-1 mode (deterministic cases "
                    "only, no artifact file)")
    ap.add_argument("--seeds", type=int, default=3,
                    help="probabilistic storm rounds in full mode")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep workdirs under DIR for post-mortem")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here (full mode artifact)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    report = soak(smoke=args.smoke, seeds=args.seeds, keep=args.keep)
    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(json.dumps({"ok": report["ok"], "cases": len(report["cases"])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
