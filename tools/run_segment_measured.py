"""Run the segment CLI in-process and report peak RSS alongside.

Substitute for ``/usr/bin/time -v`` (not present in this image): the run
summary goes to stdout exactly as the CLI prints it; a one-line JSON
``{"peak_rss_mib": ...}`` goes to stderr at exit.

Usage: python tools/run_segment_measured.py <cli args...>
  e.g. python tools/run_segment_measured.py --platform cpu segment X --out-dir Y
"""

from __future__ import annotations

import json
import os
import resource
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from land_trendr_tpu.cli import main as cli_main

    rc = cli_main(sys.argv[1:])
    peak_kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # KiB on Linux
    print(json.dumps({"peak_rss_mib": round(peak_kib / 1024, 1)}), file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
