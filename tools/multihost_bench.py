"""Pod-flow artifact: a TRUE 2-process distributed driver run at scale.

Launches two ``jax.distributed`` processes (4 virtual CPU devices each —
one 8-device cluster over a localhost coordinator), each running the real
production entry point ``run_stack`` with its LOCAL mesh over a SHARED
workdir: ``host_share`` splits the tiles between processes, the shared
manifest accumulates all of them (the v5e-pod flow of SURVEY.md §5 —
tiles, not shards, cross hosts), and this parent process then assembles
the full-scene rasters from the shared workdir and validates them
pixel-for-pixel against a single-process single-device reference run.

Writes MULTIHOST_r03.json.  Usage:
    PYTHONPATH=. python tools/multihost_bench.py [--size 512] [--tile 128]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from tests._pod_launch import launch_pod  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=512)
    ap.add_argument("--tile", type=int, default=128)
    ap.add_argument("--out", default="MULTIHOST_r03.json")
    ap.add_argument("--workroot", default=".multihost_bench")
    args = ap.parse_args()
    if args.size <= 0 or args.tile <= 0:
        ap.error("--size and --tile must be positive")

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.geotiff import read_geotiff
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import (
        RunConfig,
        assemble_outputs,
        run_stack,
        stack_from_synthetic,
    )

    # a fresh workroot every run: resumed tiles would zero the throughput
    # numbers (run_stack counts only freshly processed pixels) and stale
    # manifests from an aborted attempt would poison the comparison
    shutil.rmtree(args.workroot, ignore_errors=True)
    os.makedirs(args.workroot)
    shared = os.path.join(args.workroot, "shared_work")
    summaries = [os.path.join(args.workroot, f"summary{i}.json") for i in range(2)]

    worker = os.path.join(REPO, "tests", "_driver_worker.py")
    t0 = time.perf_counter()
    launch_pod(
        worker,
        lambda i: ["2", str(i), shared, summaries[i], str(args.size), str(args.tile)],
        timeout=3600,
        before_attempt=lambda: shutil.rmtree(shared, ignore_errors=True),
    )
    pod_wall = time.perf_counter() - t0
    per_proc = [json.load(open(p)) for p in summaries]

    spec = SceneSpec(
        width=args.size, height=args.size, year_start=1990, year_end=2013, seed=11
    )
    rs = stack_from_synthetic(make_stack(spec))
    params = LTParams(max_segments=4, vertex_count_overshoot=2)
    cfg_pod = RunConfig(
        params=params, tile_size=args.tile,
        workdir=shared, out_dir=os.path.join(args.workroot, "pod_out"),
    )
    pod_paths = assemble_outputs(rs, cfg_pod)

    # single-process single-device reference on the same scene
    cfg_ref = RunConfig(
        params=params, tile_size=args.tile,
        workdir=os.path.join(args.workroot, "ref_work"),
        out_dir=os.path.join(args.workroot, "ref_out"),
    )
    t0 = time.perf_counter()
    run_stack(rs, cfg_ref)
    ref_wall = time.perf_counter() - t0
    ref_paths = assemble_outputs(rs, cfg_ref)

    agreement = {}
    for name in ("model_valid", "n_vertices", "vertex_years", "rmse"):
        a, _, _ = read_geotiff(pod_paths[name])
        b, _, _ = read_geotiff(ref_paths[name])
        if name == "rmse":
            same = np.isclose(a, b, rtol=1e-5, atol=1e-6)
        else:
            same = a == b
        agreement[name] = round(float(np.mean(same)), 6)

    # validate BEFORE writing: a failed run must not leave a
    # complete-looking artifact on disk (explicit raises — a bare assert
    # vanishes under python -O)
    total_px = sum(s["pixels"] for s in per_proc)
    if total_px != args.size * args.size:
        raise RuntimeError(
            f"pod processed {total_px} px, expected {args.size**2} "
            "(resume-skipped tiles? stale workroot?)"
        )
    if min(agreement.values()) <= 0.999:
        raise RuntimeError(f"raster agreement too low: {agreement}")

    rec = {
        "description": (
            "True 2-process jax.distributed DRIVER run (SURVEY.md §5 pod "
            "flow scaled to localhost): each process runs run_stack on its "
            "own 4-device local mesh over a SHARED workdir; host_share "
            "splits tiles; assembly mosaics the union; rasters compared "
            "pixel-for-pixel to a single-process single-device reference."
        ),
        "platform": "cpu (8 virtual devices across 2 processes)",
        "scene": {"size": args.size, "years": 24, "tile": args.tile},
        "pod": {
            "wall_s": round(pod_wall, 1),
            "per_process": [
                {k: s[k] for k in ("pixels", "tiles_skipped_resume", "mesh_devices", "px_per_s")}
                for s in per_proc
            ],
        },
        "reference_wall_s": round(ref_wall, 1),
        "raster_agreement_fraction": agreement,
        "note": (
            "mesh-vs-single-device execution may legally flip rare f32 "
            "knife-edge decisions (ops/segment.py tolerance contract); "
            "agreement is expected ~1.0 but not bit-contractual"
        ),
    }
    from tools._measure import write_json_atomic

    write_json_atomic(args.out, rec)
    print(json.dumps(rec, indent=2))
    shutil.rmtree(args.workroot, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
