"""Autotuner bench: tuned-vs-default proof + tuning-store lifecycle.

The artifact of record for the autotuned-execution-profiles PR
(``TUNE_r15.json``): probes a profile for a synthetic scene, proves the
store lifecycle (warm reload with ZERO probes resolving identical knob
values; byte-stable profile round trip; deterministic ``"auto"``
resolution), and runs the end-to-end parity leg — a default-config
segment run vs an ``"auto"``-knob run resolving through the probed
profile — asserting **byte-identical artifacts** (the tuned knobs this
leg exercises are pure execution facts) and reporting both walls.  The
speedup claim rides the probe groups themselves: each group's report
carries ``default_s`` (the hardcoded default's median) and ``best_s``
(the winner's), and the bench asserts at least one probed group reached
``speedup >= 1.10`` — the per-stage win the profile locks in.

``--smoke`` shrinks everything to seconds scale — the tier-1 mode
``tools/perf_gate.py``'s tune leg drives.

Usage:
    python tools/tune_bench.py --out TUNE_r15.json     # full artifact
    python tools/tune_bench.py --smoke --out /tmp/t.json
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

# the ONE artifact-identity definition (array content, not npz container
# metadata) — shared with the soak so the two parity gates cannot drift
from fault_soak import _digest_workdir  # noqa: E402

#: the full-mode speedup floor at least one probed group must reach
SPEEDUP_FLOOR = 1.10


def _make_stack(size: int, ny: int):
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import stack_from_synthetic

    return stack_from_synthetic(make_stack(SceneSpec(
        width=size, height=size, year_start=2000, year_end=2000 + ny - 1,
        seed=11,
    )))


def run_bench(smoke: bool, out_path: "str | None", keep: "str | None" = None) -> dict:
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.obs.events import iter_events
    from land_trendr_tpu.runtime import RunConfig, run_stack
    from land_trendr_tpu.tune import TuningStore, autotune, resolve_config

    root = Path(keep or tempfile.mkdtemp(prefix="lt_tune_bench_"))
    root.mkdir(parents=True, exist_ok=True)
    store_dir = str(root / "store")
    size = 96 if smoke else 192
    ny = 12 if smoke else 24
    tile = 32 if smoke else 64
    reps = 2 if smoke else 3

    report: dict = {
        "smoke": smoke,
        "scene": {"size": size, "years": ny, "tile": tile},
        "legs": {},
        "invariants": {},
    }
    try:
        # -- leg 1: cold probe --------------------------------------------
        t0 = time.perf_counter()
        p1 = autotune(
            store_dir, height=size, width=size, n_years=ny,
            smoke=smoke, reps=reps,
        )
        report["legs"]["probe"] = {
            "wall_s": round(time.perf_counter() - t0, 3),
            "source": p1["source"],
            "probes": p1["probes"],
            "knobs": p1["knobs"],
            "groups": {
                g: {
                    k: r[k]
                    for k in ("ok", "probes", "default_s", "best_s", "speedup")
                    if k in r
                }
                for g, r in p1["groups"].items()
            },
        }
        speedups = [
            r.get("speedup", 1.0)
            for r in p1["groups"].values() if r.get("ok")
        ]
        report["max_group_speedup"] = max(speedups) if speedups else None
        report["invariants"]["all_groups_probed"] = all(
            r.get("ok") for r in p1["groups"].values()
        )
        # structural: every candidate set contains the default, so the
        # winner can only match or beat it
        report["invariants"]["tuned_never_worse_than_default"] = all(
            r["best_s"] <= r["default_s"] + 1e-9
            for r in p1["groups"].values() if r.get("ok")
        )
        report["invariants"]["group_speedup_floor_met"] = bool(
            speedups and max(speedups) >= SPEEDUP_FLOOR
        )

        # -- leg 2: warm store (zero probes, identical knobs) --------------
        t0 = time.perf_counter()
        p2 = autotune(
            store_dir, height=size, width=size, n_years=ny,
            smoke=smoke, reps=reps,
        )
        report["legs"]["warm"] = {
            "wall_s": round(time.perf_counter() - t0, 6),
            "source": p2["source"],
        }
        report["invariants"]["warm_zero_probes"] = p2["source"] == "store"
        report["invariants"]["warm_identical_knobs"] = (
            p2["knobs"] == p1["knobs"]
        )

        # -- leg 3: profile round trip is byte-stable ----------------------
        store = TuningStore(store_dir)
        path = store.path_for(p1["key"])
        before = Path(path).read_bytes()
        loaded = store.load(
            p1["device_kind"], p1["backend"], p1["shape_class"]
        )
        store.save(loaded)
        report["invariants"]["profile_roundtrip_byte_stable"] = (
            Path(path).read_bytes() == before
        )

        # -- leg 4: deterministic "auto" resolution ------------------------
        auto_kw = dict(
            feed_workers="auto", decode_workers="auto",
            feed_cache_mb="auto", fetch_depth="auto", upload_depth="auto",
            tune_store_dir=store_dir,
        )
        base_kw = dict(
            params=LTParams(max_segments=4, vertex_count_overshoot=2),
            tile_size=tile,
            retry_backoff_s=0.0,
        )
        probe_cfg = RunConfig(workdir="x", out_dir="y", **base_kw, **auto_kw)
        r1, i1 = resolve_config(probe_cfg, scene_shape=(size, size, ny))
        r2, i2 = resolve_config(probe_cfg, scene_shape=(size, size, ny))
        # determinism is about the resolved VALUES — age_s is a live
        # clock fact and legitimately differs between two reads
        report["invariants"]["resolution_deterministic"] = (
            r1 == r2
            and {k: v for k, v in i1.items() if k != "age_s"}
            == {k: v for k, v in i2.items() if k != "age_s"}
            and i1["source"] == "store"
        )
        report["resolved_knobs"] = i1["knobs"]

        # -- leg 5: end-to-end parity (default vs tuned) -------------------
        stack = _make_stack(size, ny)
        # warm the kernel compiles in a scratch workdir first: both timed
        # legs share one process's jit cache, so whichever ran first would
        # otherwise carry the compile and fabricate an e2e "speedup"
        wd_warm = str(root / "run_warmup")
        run_stack(stack, RunConfig(
            workdir=wd_warm, out_dir=wd_warm + "_o", **base_kw,
        ))
        wd_def = str(root / "run_default")
        t0 = time.perf_counter()
        run_stack(stack, RunConfig(
            workdir=wd_def, out_dir=wd_def + "_o", **base_kw,
        ))
        wall_def = time.perf_counter() - t0
        wd_tuned = str(root / "run_tuned")
        t0 = time.perf_counter()
        summary = run_stack(stack, RunConfig(
            workdir=wd_tuned, out_dir=wd_tuned + "_o", telemetry=True,
            **base_kw, **auto_kw,
        ))
        wall_tuned = time.perf_counter() - t0
        report["legs"]["e2e"] = {
            "default_wall_s": round(wall_def, 3),
            "tuned_wall_s": round(wall_tuned, 3),
            "e2e_speedup": round(wall_def / wall_tuned, 3),
            "tune": summary.get("tune"),
        }
        report["invariants"]["artifacts_byte_identical"] = (
            _digest_workdir(wd_def) == _digest_workdir(wd_tuned)
        )
        # the tuned run's stream must carry the zero-probe store verdict
        profs = [
            r for r in iter_events(str(Path(wd_tuned) / "events.jsonl"))
            if r["ev"] == "tune_profile"
        ]
        report["invariants"]["run_tune_profile_event"] = (
            len(profs) == 1
            and profs[0]["source"] == "store"
            and profs[0]["probes"] == 0
        )
    finally:
        if keep is None:
            shutil.rmtree(root, ignore_errors=True)

    checked = dict(report["invariants"])
    if smoke:
        # container scheduling noise owns the smoke tier's speedups; the
        # structural invariants are the smoke contract (the perf gate
        # bands the rest)
        checked.pop("group_speedup_floor_met", None)
    report["ok"] = all(checked.values())
    if out_path:
        from tools._measure import write_json_atomic

        write_json_atomic(out_path, report, trailing_newline=False)
    return report


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale mode (structural invariants only)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON report here")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep workdirs under DIR for post-mortem")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    report = run_bench(smoke=args.smoke, out_path=args.out, keep=args.keep)
    if args.out:
        print(f"wrote {args.out}")
    print(json.dumps({
        "ok": report["ok"],
        "max_group_speedup": report.get("max_group_speedup"),
        "invariants": report["invariants"],
    }, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
