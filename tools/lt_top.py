"""`lt top` — curses-free terminal status view for a running server.

Polls a live ``lt serve`` process's HTTP surface — ``/healthz`` (queue /
uptime / warm-program facts), ``/debug/jobs`` (per-job live state incl.
the running job's pipeline progress) and ``/metrics`` (the ``lt_serve_*``
and ``lt_slo_*`` instruments) — and renders a one-screen status view,
refreshed in place with plain ANSI (no curses, so it works in any dumb
terminal, a CI log, or piped to a file).  This is how a gigapixel
service run is *watchable* the way README promises runs are inspectable
in flight.

Modes:

* default — refresh every ``--interval`` seconds until Ctrl-C;
* ``--once`` — print one snapshot and exit (tests / CI / cron);
* ``--json`` — emit the merged raw snapshot as JSON instead of the
  rendered view (scripting; implies one-shot).

Exit codes: 0 ok, 2 connection/usage error (the server is down or the
debug surface is disabled).

Usage:
    python tools/lt_top.py --port 8800            # live view
    python tools/lt_top.py --port 8800 --once     # one snapshot
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

_CLEAR = "\x1b[2J\x1b[H"


def _get_json(base: str, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _get_text(base: str, path: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def parse_prom(text: str) -> list:
    """Prometheus text exposition → ``(name, labels dict, value)`` rows
    (enough of the 0.0.4 format for our own exporter's output)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            continue
        labels: dict = {}
        name = name_part
        if "{" in name_part and name_part.endswith("}"):
            name, _, raw = name_part.partition("{")
            for item in raw[:-1].split('","'):
                if "=" in item:
                    k, _, v = item.partition("=")
                    labels[k] = v.strip('"')
        try:
            value = float(value_part)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def _metric(rows: list, name: str, default: float = 0.0) -> float:
    for n, _, v in rows:
        if n == name:
            return v
    return default


def snapshot(base: str) -> dict:
    """One merged poll of the three endpoints (metrics/debug optional —
    a --no-telemetry or --no-debug-endpoints server still tops)."""
    snap: dict = {"healthz": _get_json(base, "/healthz")}
    try:
        snap["metrics"] = parse_prom(_get_text(base, "/metrics"))
    except urllib.error.HTTPError:
        snap["metrics"] = []
    try:
        snap["jobs"] = _get_json(base, "/debug/jobs")["jobs"]
    except urllib.error.HTTPError:
        # debug surface off: fall back to the plain jobs listing
        snap["jobs"] = _get_json(base, "/jobs")["jobs"]
    return snap


def _fmt_age(secs: float) -> str:
    if secs < 90:
        return f"{secs:.0f}s"
    if secs < 5400:
        return f"{secs / 60:.1f}m"
    return f"{secs / 3600:.1f}h"


def render(snap: dict) -> str:
    """The one-screen view (a plain string — the caller owns the
    terminal)."""
    h = snap["healthz"]
    rows = snap["metrics"]
    now = time.time()
    lines = []
    lines.append(
        f"lt top — uptime {_fmt_age(h.get('uptime_s', 0))}   "
        f"queue {h.get('queue_depth', '?')}   "
        f"running {h.get('running') or '-'}   "
        f"terminal {h.get('jobs_terminal', '?')}/{h.get('jobs_total', '?')}"
        f"   warm programs {h.get('warm_program_count', '?')}"
    )
    met = _metric(rows, "lt_slo_met_total")
    missed = _metric(rows, "lt_slo_missed_total")
    burn = _metric(rows, "lt_slo_burn_rate")
    rej = _metric(rows, "lt_serve_rejections_total")
    if rows:
        lines.append(
            f"slo: met {met:.0f}  missed {missed:.0f}  "
            f"burn {burn:.2f}   rejections {rej:.0f}   "
            f"warm-hit {_metric(rows, 'lt_serve_warm_hit_ratio'):.2f}"
        )
    lines.append("")
    lines.append(
        f"{'JOB':<22} {'STATE':<18} {'TENANT':<10} {'PRI':>3} "
        f"{'PHASE':<9} {'TILES':>9} {'RETRY':>5} {'STRAG':>5} "
        f"{'BKLG f/w/x/u':>12} {'AGE':>6}"
    )
    for job in snap["jobs"]:
        p = job.get("progress") or {}
        tiles = (
            f"{p.get('tiles_done', '-')}/{p.get('tiles_total', '-')}"
            if p else "-"
        )
        backlog = (
            "/".join(
                str(p.get(k, 0))
                for k in (
                    "feed_backlog", "write_backlog", "fetch_backlog",
                    "upload_backlog",
                )
            )
            if p else "-"
        )
        state = job.get("state", "?")
        if job.get("deadline_exceeded"):
            state += "!SLO"
        age = now - job.get("submitted_t", now)
        lines.append(
            f"{job.get('job_id', '?'):<22} {state:<18} "
            f"{job.get('tenant', '?'):<10} {job.get('priority', 0):>3} "
            f"{p.get('phase', '-'):<9} {tiles:>9} "
            f"{p.get('retries', '-') if p else '-':>5} "
            f"{p.get('stragglers', '-') if p else '-':>5} {backlog:>12} "
            f"{_fmt_age(age):>6}"
        )
    if not snap["jobs"]:
        lines.append("(no jobs)")
    return "\n".join(lines)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port", type=int, required=True,
                    help="the server's job-API port (from the startup line)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="the server's job-API host (loopback)")
    ap.add_argument("--interval", type=float, default=2.0, metavar="SEC",
                    help="refresh period for the live view")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (tests / CI)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw merged snapshot as JSON (one-shot)")
    args = ap.parse_args(argv)
    base = f"http://{args.host}:{args.port}"

    try:
        if args.json:
            snap = snapshot(base)
            snap["metrics"] = [
                {"name": n, "labels": l, "value": v}
                for n, l, v in snap["metrics"]
            ]
            print(json.dumps(snap, indent=2, default=str))
            return 0
        if args.once:
            print(render(snapshot(base)))
            return 0
        while True:
            view = render(snapshot(base))
            sys.stdout.write(_CLEAR + view + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
        print(f"error: cannot poll {base}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
