"""`lt top` — curses-free terminal status view for a server or a fleet.

Polls live ``lt serve`` processes' HTTP surfaces — ``/healthz`` (queue /
uptime / warm-program facts, active alerts), ``/debug/jobs`` (per-job
live state incl. the running job's pipeline progress) and ``/metrics``
(the ``lt_serve_*`` and ``lt_slo_*`` instruments) — and renders a
one-screen status view, refreshed in place with plain ANSI (no curses,
so it works in any dumb terminal, a CI log, or piped to a file).  This
is how a gigapixel service run is *watchable* the way README promises
runs are inspectable in flight.

Targets (one or many — the fleet is first-class):

* ``--port N`` (with ``--host``) — one server, the classic view;
* ``--url BASE`` (repeatable) — N replicas: per-replica rows under an
  AGGREGATE header whose instruments merge through the fleet plane's
  per-instrument policy table (``land_trendr_tpu.obs.aggregate`` —
  counters sum, burn rates take the pod max; one merge policy, no
  duplicate), plus every replica's jobs and the union of active alerts;
* ``--dir TELEMETRY_DIR`` — no HTTP at all: fold the fleet snapshot
  files under a shared telemetry directory (standalone pod runs
  included) and render the ``lt_fleet`` report.

Modes:

* default — refresh every ``--interval`` seconds until Ctrl-C;
* ``--once`` — print one snapshot and exit (tests / CI / cron);
* ``--json`` — emit the merged raw snapshot as JSON instead of the
  rendered view (scripting; implies one-shot).

Exit codes: 0 ok, 2 connection/usage error (the server is down or the
debug surface is disabled).

Usage:
    python tools/lt_top.py --port 8800                  # one server
    python tools/lt_top.py --url http://127.0.0.1:8800 \\
                           --url http://127.0.0.1:8801  # a fleet
    python tools/lt_top.py --dir lt_serve/telemetry     # shared-FS pod
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

_CLEAR = "\x1b[2J\x1b[H"


def _get_json(base: str, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _get_text(base: str, path: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def parse_prom(text: str, types: "dict | None" = None) -> list:
    """Prometheus text exposition → ``(name, labels dict, value)`` rows
    (enough of the 0.0.4 format for our own exporter's output).  With a
    ``types`` dict, ``# TYPE`` lines fill it ``{family: kind}`` — what
    the fleet merge needs to apply the right per-instrument policy."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("# TYPE ") and types is not None:
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            continue
        labels: dict = {}
        name = name_part
        if "{" in name_part and name_part.endswith("}"):
            name, _, raw = name_part.partition("{")
            for item in raw[:-1].split('","'):
                if "=" in item:
                    k, _, v = item.partition("=")
                    labels[k] = v.strip('"')
        try:
            value = float(value_part)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def prom_instruments(text: str) -> list:
    """Exposition text → the instrument-dict shape
    ``land_trendr_tpu.obs.aggregate.merge_instruments`` folds.

    Histogram series are reconstructed whole: the cumulative
    ``_bucket`` rows of one ``(family, labels)`` series de-cumulate
    back into the per-bucket counts ``merge_instruments`` sums
    elementwise, with ``_sum`` / ``_count`` riding along — so a fleet
    header can answer percentiles from the MERGED distribution
    (:func:`~land_trendr_tpu.obs.aggregate.histogram_quantile`), not
    just totals.  A series whose rows are torn (no ``+Inf`` bucket, or
    bucket counts that decumulate negative) is dropped rather than
    folded wrong.
    """
    types: dict = {}
    rows = parse_prom(text, types=types)
    out: list = []
    hists: dict = {}  # (family, labels-sans-le) → {les, sum, count}
    for name, labels, value in rows:
        kind = types.get(name)
        if kind is None:
            kind = "gauge"  # untyped rows merge conservatively
            for suffix in ("_bucket", "_sum", "_count"):
                family = name[: -len(suffix)] if name.endswith(suffix) else None
                if family is not None and types.get(family) == "histogram":
                    lab = {k: v for k, v in labels.items() if k != "le"}
                    key = (family, tuple(sorted(lab.items())))
                    h = hists.setdefault(
                        key, {"labels": lab, "les": {}, "sum": 0.0,
                              "count": 0},
                    )
                    if suffix == "_bucket":
                        h["les"][labels.get("le", "+Inf")] = value
                    elif suffix == "_sum":
                        h["sum"] = value
                    else:
                        h["count"] = int(value)
                    kind = None
                    break
            if kind is None:
                continue  # histogram row: folded into hists above
        out.append({"name": name, "kind": kind, "labels": labels,
                    "value": value})
    for (family, _), h in sorted(hists.items()):
        les = h["les"]
        if "+Inf" not in les:
            continue  # torn series: no total bucket to close against
        try:
            by_val = {float(le): v for le, v in les.items() if le != "+Inf"}
        except ValueError:
            continue  # an unparseable le label: drop the torn series
        bounds = sorted(by_val)
        cum = [by_val[b] for b in bounds]
        cum.append(les["+Inf"])
        buckets, prev = [], 0.0
        for c in cum:
            buckets.append(int(c - prev))
            prev = c
        if any(b < 0 for b in buckets):
            continue  # torn series: cumulative counts went backwards
        out.append({
            "name": family, "kind": "histogram", "labels": h["labels"],
            "sum": h["sum"], "count": h["count"], "bounds": bounds,
            "buckets": buckets,
        })
    return out


def _metric(rows: list, name: str, default: float = 0.0) -> float:
    for n, _, v in rows:
        if n == name:
            return v
    return default


def snapshot(base: str) -> dict:
    """One merged poll of the three endpoints (metrics/debug optional —
    a --no-telemetry or --no-debug-endpoints server still tops)."""
    snap: dict = {"healthz": _get_json(base, "/healthz"), "base": base}
    try:
        text = _get_text(base, "/metrics")
        snap["metrics"] = parse_prom(text)
        snap["metrics_text"] = text
    except urllib.error.HTTPError:
        snap["metrics"] = []
        snap["metrics_text"] = ""
    try:
        snap["jobs"] = _get_json(base, "/debug/jobs")["jobs"]
    except urllib.error.HTTPError:
        # debug surface off: fall back to the plain jobs listing
        snap["jobs"] = _get_json(base, "/jobs")["jobs"]
    try:
        # request tracing: recent terminal requests, slowest first
        # (trace ids + blame splits; absent on debug-walled servers)
        snap["requests"] = _get_json(base, "/debug/requests")["requests"]
    except (urllib.error.HTTPError, KeyError):
        snap["requests"] = []
    return snap


def _fmt_age(secs: float) -> str:
    if secs < 90:
        return f"{secs:.0f}s"
    if secs < 5400:
        return f"{secs / 60:.1f}m"
    return f"{secs / 3600:.1f}h"


def render(snap: dict) -> str:
    """The one-screen view (a plain string — the caller owns the
    terminal)."""
    h = snap["healthz"]
    rows = snap["metrics"]
    now = time.time()
    lines = []
    lines.append(
        f"lt top — uptime {_fmt_age(h.get('uptime_s', 0))}   "
        f"queue {h.get('queue_depth', '?')}   "
        f"running {h.get('running') or '-'}   "
        f"terminal {h.get('jobs_terminal', '?')}/{h.get('jobs_total', '?')}"
        f"   warm programs {h.get('warm_program_count', '?')}"
    )
    tune = h.get("tune")
    if tune:
        age = tune.get("age_s")
        lines.append(
            f"tune: {tune.get('key') or 'defaults'} "
            f"src {tune.get('source', '?')}"
            + (
                f" age {_fmt_age(age)}"
                if isinstance(age, (int, float)) else ""
            )
        )
    met = _metric(rows, "lt_slo_met_total")
    missed = _metric(rows, "lt_slo_missed_total")
    burn = _metric(rows, "lt_slo_burn_rate")
    rej = _metric(rows, "lt_serve_rejections_total")
    if rows:
        lines.append(
            f"slo: met {met:.0f}  missed {missed:.0f}  "
            f"burn {burn:.2f}   rejections {rej:.0f}   "
            f"warm-hit {_metric(rows, 'lt_serve_warm_hit_ratio'):.2f}"
        )
    launches = _metric(rows, "lt_batch_launches_total")
    if launches:
        # cross-job batching (serve/batching): how much per-launch
        # overhead the dispatcher is amortising right now
        lines.append(
            f"batch: launches {launches:.0f}  "
            f"jobs coalesced "
            f"{_metric(rows, 'lt_batch_jobs_coalesced_total'):.0f}  "
            f"demuxed tiles "
            f"{_metric(rows, 'lt_batch_demux_tiles_total'):.0f}  "
            f"occupancy {_metric(rows, 'lt_batch_occupancy'):.2f}"
        )
    lines.append("")
    lines.append(
        f"{'JOB':<22} {'TRACE':<10} {'STATE':<18} {'TENANT':<10} "
        f"{'PRI':>3} "
        f"{'PHASE':<9} {'TILES':>9} {'RETRY':>5} {'STRAG':>5} "
        f"{'STEAL':>5} {'SPEC':>4} {'BKLG f/w/x/u':>12} {'BATCH':>7} "
        f"{'AGE':>6}"
    )
    for job in snap["jobs"]:
        p = job.get("progress") or {}
        tiles = (
            f"{p.get('tiles_done', '-')}/{p.get('tiles_total', '-')}"
            if p else "-"
        )
        backlog = (
            "/".join(
                str(p.get(k, 0))
                for k in (
                    "feed_backlog", "write_backlog", "fetch_backlog",
                    "upload_backlog",
                )
            )
            if p else "-"
        )
        state = job.get("state", "?")
        if job.get("deadline_exceeded"):
            state += "!SLO"
        # the running leader's live batch state: jobs sharing its
        # launch and the padded-pixel occupancy ("3@0.87"); solo and
        # queued jobs show "-"
        bj = p.get("batch_jobs", 0) if p else 0
        batch = (
            f"{bj}@{p.get('batch_occupancy', 0.0):.2f}"
            if isinstance(bj, int) and bj > 1 else "-"
        )
        age = now - job.get("submitted_t", now)
        lines.append(
            f"{job.get('job_id', '?'):<22} "
            f"{str(job.get('trace_id') or '-')[:10]:<10} {state:<18} "
            f"{job.get('tenant', '?'):<10} {job.get('priority', 0):>3} "
            f"{p.get('phase', '-'):<9} {tiles:>9} "
            f"{p.get('retries', '-') if p else '-':>5} "
            f"{p.get('stragglers', '-') if p else '-':>5} "
            f"{p.get('tiles_stolen', '-') if p else '-':>5} "
            f"{p.get('tiles_speculated', '-') if p else '-':>4} "
            f"{backlog:>12} "
            f"{batch:>7} "
            f"{_fmt_age(age):>6}"
        )
    if not snap["jobs"]:
        lines.append("(no jobs)")
    alerts = snap["healthz"].get("alerts") or []
    if alerts:
        lines.append("")
        lines.append("ALERTS:")
        for a in alerts:
            lines.append(
                f"  FIRING    {a.get('rule', '?')} (value "
                f"{a.get('value')}, threshold {a.get('threshold')})"
            )
    return "\n".join(lines)


def render_router(snap: dict) -> str:
    """The router view (``/healthz`` answered ``"router": true``): the
    fleet aggregate from ONE target — per-tenant queue depths, the
    replica table, the scaler state, and the router job listing."""
    h = snap["healthz"]
    rows = snap["metrics"]
    now = time.time()
    lines = [
        f"lt top — router, uptime {_fmt_age(h.get('uptime_s', 0))}   "
        f"queue {h.get('queue_depth', '?')}   "
        f"routed {h.get('routed', '?')}   "
        f"terminal {h.get('jobs_terminal', '?')}/{h.get('jobs_total', '?')}"
    ]
    if rows:
        lines.append(
            f"routing: forwards {_metric(rows, 'lt_router_jobs_routed_total'):.0f}  "
            f"warm {_metric(rows, 'lt_router_warm_routed_total'):.0f}  "
            f"rerouted {_metric(rows, 'lt_router_rerouted_total'):.0f}  "
            f"throttled {_metric(rows, 'lt_router_throttled_total'):.0f}"
        )
    tenants = h.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append(
            f"{'TENANT':<14} {'QUEUED':>6} {'ROUTED':>6} {'WEIGHT':>6} "
            f"{'DEFICIT':>7}"
        )
        for name in sorted(tenants):
            t = tenants[name]
            lines.append(
                f"{name:<14} {t.get('queued', 0):>6} "
                f"{t.get('routed', 0):>6} {t.get('weight', 1):>6g} "
                f"{t.get('deficit', 0):>7}"
            )
    lines.append("")
    lines.append(
        f"{'REPLICA':<8} {'STATE':<9} {'INFL':>4} {'WARM':>4} "
        f"{'QUEUE':>5} {'FAILS':>5} {'HEALTH':>7} {'BASE'}"
    )
    for r in h.get("replicas") or []:
        age = r.get("health_age_s")
        lines.append(
            f"{r.get('replica', '?'):<8} {r.get('state', '?'):<9} "
            f"{r.get('inflight', 0):>4} {r.get('warm_keys', 0):>4} "
            f"{str(r.get('queue_depth', '-')):>5} {r.get('fails', 0):>5} "
            f"{_fmt_age(age) if isinstance(age, (int, float)) else '-':>7} "
            f"{r.get('base', '?')}"
        )
    scaler = h.get("scaler")
    if scaler:
        lines.append("")
        lines.append(
            f"scaler: burn {scaler.get('burn')}  bounds "
            f"[{scaler.get('min_replicas')}, {scaler.get('max_replicas')}]"
            f"  firing {scaler.get('firing') or '-'}  "
            f"last action "
            f"{_fmt_age(now - scaler['last_action_t']) + ' ago' if scaler.get('last_action_t') else 'never'}"
        )
    rec = h.get("recovery")
    if h.get("recovering") or rec:
        jrn = h.get("journal") or {}
        lines.append("")
        if h.get("recovering"):
            lines.append("RECOVERY: in progress (submissions answer 503)")
        else:
            lines.append(
                f"RECOVERY: replayed {rec.get('replayed', 0)}  "
                f"relayed {rec.get('relayed', 0)}  "
                f"requeued {rec.get('requeued', 0)}  "
                f"reattached {rec.get('reattached', 0)}  "
                f"deduped {rec.get('deduped', 0)}  "
                f"in {rec.get('recovery_s', 0):.3f}s"
                f"{'  (clean shutdown)' if rec.get('clean') else ''}  "
                f"journal seg {jrn.get('segment', '?')} "
                f"({jrn.get('segments', '?')} on disk)"
            )
    lines.append("")
    lines.append(
        f"{'JOB':<16} {'TRACE':<10} {'STATE':<18} {'TENANT':<10} "
        f"{'REPLICA':<8} {'ATT':>3} {'AGE':>6}"
    )
    for job in snap["jobs"]:
        age = now - job.get("submitted_t", now)
        lines.append(
            f"{job.get('job_id', '?'):<16} "
            f"{str(job.get('trace_id') or '-')[:10]:<10} "
            f"{job.get('state', '?'):<18} "
            f"{job.get('tenant', '?'):<10} "
            f"{str(job.get('replica') or '-'):<8} "
            f"{job.get('attempts', 0):>3} {_fmt_age(age):>6}"
        )
    if not snap["jobs"]:
        lines.append("(no jobs)")
    slow = (snap.get("requests") or [])[:5]
    if slow:
        lines.append("")
        lines.append("SLOWEST REQUESTS (lt_request <trace> <workdir>):")
        for r in slow:
            blame = r.get("blame") or {}
            split = " ".join(
                f"{k}={v:.2f}s" for k, v in sorted(blame.items())
                if isinstance(v, (int, float)) and v > 0
            )
            lines.append(
                f"  {str(r.get('trace_id') or '?'):<18} "
                f"{r.get('status', '?'):<10} "
                f"{r.get('latency_s', 0):>8.2f}s  "
                f"hops {r.get('hops', '-')}  {split}"
            )
    return "\n".join(lines)


def render_fleet(snaps: list) -> str:
    """N replica snapshots → one view: the AGGREGATE header (instruments
    merged through the fleet plane's per-instrument policy table —
    ``obs.aggregate.merge_instruments``, the single copy of that
    logic), per-replica rows, every replica's jobs, and the union of
    active alerts."""
    from land_trendr_tpu.obs.aggregate import (
        histogram_quantile,
        merge_instruments,
    )

    merged, _ = merge_instruments(
        (float(i), prom_instruments(s.get("metrics_text", "")))
        for i, s in enumerate(snaps)
    )
    by_name = {
        m["name"]: m["value"] for m in merged
        if not m.get("labels") and m.get("value") is not None
    }

    def agg(name: str, default: float = 0.0) -> float:
        return float(by_name.get(name, default))

    lines = [
        f"lt top — fleet of {len(snaps)} replica(s)   "
        f"queue {agg('lt_serve_queue_depth'):.0f}   "
        f"running {agg('lt_serve_running'):.0f}   "
        f"slo: met {agg('lt_slo_met_total'):.0f} "
        f"missed {agg('lt_slo_missed_total'):.0f} "
        f"burn(max) {agg('lt_slo_burn_rate'):.2f}   "
        f"rejections {agg('lt_serve_rejections_total'):.0f}"
    ]
    # fleet-wide latency percentiles from the MERGED job-seconds
    # distribution (per-replica percentiles don't average; merged
    # buckets are the one honest fold)
    job_hist = next(
        (m for m in merged
         if m["name"] == "lt_serve_job_seconds"
         and m.get("kind") == "histogram" and not m.get("labels")),
        None,
    )
    if job_hist is not None and job_hist.get("count", 0) > 0:
        p50 = histogram_quantile(job_hist, 0.50)
        p99 = histogram_quantile(job_hist, 0.99)
        lines.append(
            f"latency (merged, {job_hist['count']} jobs): "
            f"p50 ~{p50:.2f}s  p99 ~{p99:.2f}s"
        )
    lines.append("")
    lines.append(
        f"{'REPLICA':<28} {'UP':>6} {'QUEUE':>5} {'RUN':>3} "
        f"{'TERM':>9} {'WARM':>4} {'BURN':>5} {'ALRT':>4}"
    )
    alerts: list = []
    for s in snaps:
        h = s["healthz"]
        rows = s["metrics"]
        for a in h.get("alerts") or []:
            alerts.append({**a, "replica": s.get("base", "?")})
        lines.append(
            f"{s.get('base', '?'):<28} "
            f"{_fmt_age(h.get('uptime_s', 0)):>6} "
            f"{h.get('queue_depth', '?'):>5} "
            f"{1 if h.get('running') else 0:>3} "
            f"{str(h.get('jobs_terminal', '?')) + '/' + str(h.get('jobs_total', '?')):>9} "
            f"{h.get('warm_program_count', '?'):>4} "
            f"{_metric(rows, 'lt_slo_burn_rate'):>5.2f} "
            f"{len(h.get('alerts') or []):>4}"
        )
    # which tuning profile each replica's auto-knob jobs resolved
    # through — the mixed tuned/untuned fleet made visible
    if any(s["healthz"].get("tune") for s in snaps):
        lines.append("")
        lines.append("tune profiles:")
        for s in snaps:
            t = s["healthz"].get("tune")
            if not t:
                continue
            age = t.get("age_s")
            lines.append(
                f"  {s.get('base', '?')} {t.get('key') or 'defaults'} "
                f"src {t.get('source', '?')}"
                + (
                    f" age {_fmt_age(age)}"
                    if isinstance(age, (int, float)) else ""
                )
            )
    lines.append("")
    jobs = [
        {**job, "_replica": s.get("base", "?")}
        for s in snaps for job in s["jobs"]
    ]
    if jobs:
        lines.append(f"{'JOB':<22} {'STATE':<18} {'TENANT':<10} {'REPLICA'}")
        for job in jobs:
            state = job.get("state", "?")
            if job.get("deadline_exceeded"):
                state += "!SLO"
            lines.append(
                f"{job.get('job_id', '?'):<22} {state:<18} "
                f"{job.get('tenant', '?'):<10} {job['_replica']}"
            )
    else:
        lines.append("(no jobs)")
    if alerts:
        lines.append("")
        lines.append("ALERTS:")
        for a in alerts:
            lines.append(
                f"  FIRING    {a.get('rule', '?')} on "
                f"{a.get('replica', '?')} (value {a.get('value')}, "
                f"threshold {a.get('threshold')})"
            )
    return "\n".join(lines)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port", type=int, default=None,
                    help="one server's job-API port (from the startup line)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="the server's job-API host (loopback)")
    ap.add_argument("--url", action="append", default=[], metavar="BASE",
                    help="a replica's base URL (repeatable — two or more "
                    "render the fleet view with an aggregate header)")
    ap.add_argument("--dir", default=None, metavar="TELEMETRY_DIR",
                    help="no HTTP: fold the fleet snapshot files under a "
                    "shared telemetry directory (lt_fleet's view)")
    ap.add_argument("--interval", type=float, default=2.0, metavar="SEC",
                    help="refresh period for the live view")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (tests / CI)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw merged snapshot as JSON (one-shot)")
    args = ap.parse_args(argv)

    bases = list(args.url)
    if args.port is not None:
        bases.append(f"http://{args.host}:{args.port}")
    if bool(bases) == bool(args.dir):
        print(
            "error: pick a target — --port/--url (HTTP) or --dir "
            "(telemetry directory)",
            file=sys.stderr,
        )
        return 2

    if args.dir:
        # shared-FS fleet mode: the lt_fleet report over the snapshot
        # files (one view implementation — not a second copy here)
        import lt_fleet

        from land_trendr_tpu.obs import aggregate

        if not os.path.isdir(args.dir):
            print(f"error: {args.dir} is not a directory", file=sys.stderr)
            return 2
        try:
            if args.json:
                print(json.dumps(
                    aggregate.fold_dir(args.dir), indent=2, default=str
                ))
                return 0
            if args.once:
                print(lt_fleet.render(aggregate.fold_dir(args.dir)))
                return 0
            while True:
                view = lt_fleet.render(aggregate.fold_dir(args.dir))
                sys.stdout.write(_CLEAR + view + "\n")
                sys.stdout.flush()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    def poll() -> "dict | list":
        if len(bases) == 1:
            return snapshot(bases[0])
        return [snapshot(b) for b in bases]

    def show(polled) -> str:
        if isinstance(polled, dict):
            # a router target renders the fleet aggregate itself
            # (per-tenant queues, replica table, scaler state)
            if polled["healthz"].get("router"):
                return render_router(polled)
            return render(polled)
        return render_fleet(polled)

    try:
        if args.json:
            polled = poll()
            snaps = [polled] if isinstance(polled, dict) else polled
            for snap in snaps:
                snap["metrics"] = [
                    {"name": n, "labels": l, "value": v}
                    for n, l, v in snap["metrics"]
                ]
                snap.pop("metrics_text", None)
            print(json.dumps(polled, indent=2, default=str))
            return 0
        if args.once:
            print(show(poll()))
            return 0
        while True:
            view = show(poll())
            sys.stdout.write(_CLEAR + view + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
        print(f"error: cannot poll {', '.join(bases)}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
