"""Perf-regression smoke gate: bench smokes vs committed artifact bands.

ROADMAP item 5's regression-gate down-payment: the feed/fetch/upload
benches each have a ``--smoke`` tier-1 mode, but until now nothing
FAILED when a PR regressed the structural wins their committed artifacts
record (``FEED_r07.json``, ``FETCH_r08.json``, ``UPLOAD_r10.json``).
This tool runs the three smokes into a temp dir and checks each against
bands **derived from the committed artifact**, chosen to be robust to
this container's scheduler noise:

* structural invariants are exact — parity flags true, packed
  transfers-per-tile == 1, warm-store decode fully skipped
  (hit rate ≈ 100%);
* ratio invariants are banded — a smoke speedup / hit rate must reach a
  fraction of the committed value (a real regression to 1.0× fails; a
  noisy-but-working run passes).

Exit 0 = all bands met, 1 = regression (failed checks listed), 2 =
usage/IO error.  Wired into tier-1 via ``tests/test_upload.py``.

Usage:
    python tools/perf_gate.py            # smoke benches vs committed bands
    python tools/perf_gate.py --json     # machine-readable verdict only
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

#: committed artifacts of record — the baselines the bands derive from
FEED_BASELINE = REPO / "FEED_r07.json"
FETCH_BASELINE = REPO / "FETCH_r08.json"
UPLOAD_BASELINE = REPO / "UPLOAD_r10.json"
SERVE_BASELINE = REPO / "SERVE_r11.json"
FLIGHT_BASELINE = REPO / "FLIGHT_r12.json"
CAPACITY_BASELINE = REPO / "CAPACITY_r17.json"
BATCH_BASELINE = REPO / "BATCH_r18.json"

#: a smoke ratio must reach this fraction of its committed value — loose
#: enough for a 2-core container's noise, tight enough that a regression
#: to parity (1.0×) always fails
RATIO_BAND = 1 / 3
#: speedup floor even when the band would dip below it (a "speedup" of
#: 1.0 means the optimization is off, whatever the baseline said)
SPEEDUP_FLOOR = 1.15
#: batched-launch padded-pixel occupancy floor: the bench's identical
#: small-AOI flood tiles evenly, so real packing sits at ~1.0 — well
#: under-filled launches mean the batch shape regressed
BATCH_OCCUPANCY_FLOOR = 0.9
#: admission-journal commit bound, min-of-reps milliseconds per append:
#: one json.dumps + one O_APPEND os.write on a local disk sits well
#: under a millisecond — a 5ms min-of-reps means the append path grew
#: real work (fsync, lock convoy, rotation on every record), while a
#: loaded single-core box's scheduler noise stays inside the band
JOURNAL_APPEND_MAX_MS = 5.0


def _hit_rate(stats: dict) -> float | None:
    lookups = stats.get("hits", 0) + stats.get("misses", 0)
    return stats.get("hits", 0) / lookups if lookups else None


#: trace-assembly leg: synthetic pod shape (events scale linearly with
#: tiles — ~7 events/tile/host) and the bands.  The throughput floor is
#: deliberately an order of magnitude under a cold local measurement
#: (~100k events/s): the gate fails an accidentally-quadratic assembler,
#: not a noisy container.
TRACE_TILES_PER_HOST = 400
TRACE_HOSTS = 2
TRACE_SKEW_S = 1800.5
TRACE_MIN_EVENTS_PER_S = 5_000


def _synth_pod_stream(
    path: str, pidx: int, anchor_wall: float, anchor_mono: float,
    tiles: range, straggle_last: bool,
) -> int:
    """One schema-valid per-host event stream for the trace leg (spans +
    lifecycle per tile, one straggler on the lagging host); returns the
    event count."""
    import json as _json

    recs: list = []

    def ev(evname: str, dt: float, **fields) -> None:
        recs.append({
            "ev": evname,
            "t_wall": round(anchor_wall + dt, 6),
            "t_mono": round(anchor_mono + dt, 6),
            **fields,
        })

    ev("run_start", 0.0, schema=1, fingerprint="perfgate-trace", pid=1000 + pidx,
       host=f"gate-host-{pidx}", process_index=pidx, process_count=TRACE_HOSTS,
       tiles_total=len(tiles) * TRACE_HOSTS, tiles_todo=len(tiles),
       tiles_skipped_resume=0, mesh_devices=1, impl="xla",
       run_id=f"gatetrace{pidx:03d}", anchor_wall=anchor_wall,
       anchor_mono=anchor_mono)
    t = 0.05
    for n, tile in enumerate(tiles):
        slow = straggle_last and n == len(tiles) - 1
        compute_s = 0.25 if slow else 0.01
        ev("span", t + 0.002, name="feed", tile_id=tile,
           start=round(anchor_mono + t, 6), end=round(anchor_mono + t + 0.002, 6))
        ev("tile_start", t + 0.003, tile_id=tile, attempt=1)
        ev("span", t + 0.004, name="upload", tile_id=tile,
           start=round(anchor_mono + t + 0.003, 6),
           end=round(anchor_mono + t + 0.004, 6), attempt=1)
        done = t + 0.004 + compute_s
        ev("tile_done", done, tile_id=tile, px=400, compute_s=compute_s,
           px_per_s=round(400 / compute_s, 1), feed_backlog=1, write_backlog=0)
        ev("span", done + 0.001, name="fetch", tile_id=tile,
           start=round(anchor_mono + done, 6),
           end=round(anchor_mono + done + 0.001, 6))
        ev("write_done", done + 0.004, tile_id=tile, bytes=1024,
           record_s=0.003)
        if slow:
            ev("tile_straggler", done + 0.004, tile_id=tile,
               duration_s=compute_s, threshold_s=0.05, median_s=0.01,
               in_flight=False)
        t = done + 0.005
    ev("run_done", t, status="ok", tiles_done=len(tiles),
       pixels=400 * len(tiles), wall_s=round(t, 4),
       px_per_s=round(400 * len(tiles) / t, 1), fit_rate=0.8)
    with open(path, "w") as f:
        for r in recs:
            f.write(_json.dumps(r, separators=(",", ":")) + "\n")
    return len(recs)


def run_trace_leg(workdir: str, check) -> None:
    """Pod-trace assembly checks (obs/spans + tools/lt_trace).

    Structural, exact: two synthetic skewed-clock host streams must lint
    clean against the schema, assemble into one offset-corrected
    monotone trace with the straggler and critical path folded, and
    export a well-formed Chrome trace; the assembler's throughput is
    banded so an accidentally-quadratic fold fails here rather than on
    a real gigarun stream.  Callable on its own (``tests/test_spans``)
    — it needs no bench baselines.
    """
    import contextlib
    import io
    import time as _time

    import lt_trace
    from check_events_schema import value_lints

    from land_trendr_tpu.obs.events import validate_events_file
    from land_trendr_tpu.obs.spans import assemble_pod_trace

    stream_paths: list = []
    n_events = 0
    for pidx in range(TRACE_HOSTS):
        p = str(Path(workdir) / f"gate_trace.p{pidx}.events.jsonl")
        n_events += _synth_pod_stream(
            p, pidx,
            anchor_wall=1.7e9 + pidx * TRACE_SKEW_S,
            anchor_mono=100.0 + pidx * 7000.0,
            tiles=range(pidx * TRACE_TILES_PER_HOST,
                        (pidx + 1) * TRACE_TILES_PER_HOST),
            straggle_last=pidx == TRACE_HOSTS - 1,
        )
        stream_paths.append(p)
    lint_errs = [
        e for p in stream_paths
        for e in validate_events_file(p, extra=value_lints())
    ]
    check(
        "trace.streams_schema_valid", not lint_errs,
        f"{n_events} synthetic events lint clean ({lint_errs[:2]})",
    )
    t0 = _time.perf_counter()
    trace = assemble_pod_trace(stream_paths)
    assemble_s = _time.perf_counter() - t0
    t0s = [s["t0"] for s in trace["spans"]]
    skew = trace["hosts"][-1].get("wall_skew_s")
    check(
        "trace.assembled",
        len(trace["hosts"]) == TRACE_HOSTS and len(trace["spans"]) > 0
        and trace["malformed"] == 0,
        f"{len(trace['spans'])} spans from {TRACE_HOSTS} hosts",
    )
    # causality, checked against the GENERATOR's known timeline (sorted
    # t0s / non-negative durs alone are true by construction — the
    # assembler sorts and clamps): every tile's stages must land in
    # pipeline order, and every span must sit inside the synthetic run's
    # ~10s envelope — a mis-anchored fold (wall instead of mono, a
    # host's anchor not subtracted) throws spans out by 1e3–1e9 seconds
    by_tile: dict = {}
    for s in trace["spans"]:
        if s["name"] in ("feed", "upload", "compute", "fetch", "write"):
            by_tile.setdefault((s["file"], s["tile"]), {})[s["name"]] = s["t0"]
    order = ("feed", "upload", "compute", "fetch", "write")
    complete = [s for s in by_tile.values() if len(s) == len(order)]
    pipeline_ok = len(complete) == TRACE_HOSTS * TRACE_TILES_PER_HOST and all(
        tuple(sorted(stages, key=stages.get)) == order for stages in complete
    )
    t_env = max((s["t0"] + s["dur"] for s in trace["spans"]), default=-1.0)
    check(
        "trace.monotone",
        t0s == sorted(t0s) and pipeline_ok and 0.0 <= t_env < 60.0,
        f"per-tile stages in pipeline order across {len(by_tile)} tiles, "
        f"all spans inside the run envelope (max end {t_env:.3f}s)",
    )
    check(
        "trace.skew_corrected",
        skew is not None and abs(skew - TRACE_SKEW_S) < 1.0
        and min(t0s, default=float("inf")) < 1.0,
        f"reported wall skew {skew}s (injected {TRACE_SKEW_S}s), "
        "activity aligned at the run_start origin",
    )
    # .get(): a degenerate assembly (no host wall → no critical_path key)
    # is exactly the regression this leg gates — it must read as a clean
    # FAIL row, never a KeyError traceback that loses the --json verdict
    check(
        "trace.straggler_folded",
        trace["pod"]["stragglers"] == 1
        and trace["pod"].get("critical_path") is not None,
        f"pod stragglers={trace['pod']['stragglers']}, critical path "
        f"bound={(trace['pod'].get('critical_path') or {}).get('bound_stage')}",
    )
    ev_per_s = n_events / assemble_s if assemble_s > 0 else float("inf")
    check(
        "trace.overhead",
        ev_per_s >= TRACE_MIN_EVENTS_PER_S,
        f"assembled {n_events} events in {assemble_s:.3f}s "
        f"({ev_per_s:,.0f} ev/s vs floor {TRACE_MIN_EVENTS_PER_S:,})",
    )
    chrome_out = str(Path(workdir) / "gate_pod_trace.json")
    # lt_trace prints its report to stdout; the gate's --json contract
    # promises ONLY the verdict there, so the report is swallowed
    with contextlib.redirect_stdout(io.StringIO()):
        rc = lt_trace.main([*stream_paths, "--trace", chrome_out])
    ok_chrome = False
    if rc == 0 and Path(chrome_out).exists():
        chrome = json.loads(Path(chrome_out).read_text())
        xs = [e for e in chrome.get("traceEvents", []) if e.get("ph") == "X"]
        ok_chrome = bool(xs) and all(e["ts"] >= 0 for e in xs)
    check(
        "trace.chrome_export",
        ok_chrome,
        f"lt_trace rc={rc}, slices well-formed in {chrome_out}",
    )


#: request-tracing leg: synthetic fleet shape (router stream + replica
#: serve stream + pinned job run stream, ONE re-routed trace among many
#: single-hop ones) and the bands.  The assembler floor reuses the
#: trace leg's 5k ev/s convention — it fails an accidentally-quadratic
#: fold, not a noisy container; the stamp-overhead ceiling is the
#: FLIGHT baseline's documented noise band (trace stamping rides the
#: same emit path the flight artifact bounded).
REQTRACE_TRACES = 40
REQTRACE_TILES = 20
REQTRACE_MIN_EVENTS_PER_S = TRACE_MIN_EVENTS_PER_S


def _synth_reqtrace_streams(workdir: str) -> "tuple[list[str], str]":
    """Write a deterministic router + replica + run stream set:
    REQTRACE_TRACES requests, the LAST one re-routed (two forward hops,
    the first ok=false), every trace's run scope stamped with its id.
    Returns ``(stream paths, the re-routed trace_id)``."""
    import json as _json

    aw, am = 1.75e9, 500.0
    rt, sv, rn = [], [], []

    def ev(recs, evname, dt, **fields):
        recs.append({
            "ev": evname, "t_wall": round(aw + dt, 6),
            "t_mono": round(am + dt, 6), **fields,
        })

    def rs(recs, fp, **extra):
        ev(recs, "run_start", extra.pop("dt", 0.0), schema=1,
           fingerprint=fp, pid=7000, host="gate-fleet",
           process_index=0, process_count=1, tiles_total=0, tiles_todo=0,
           tiles_skipped_resume=0, mesh_devices=1, impl=fp,
           run_id=f"gatereq{fp}", anchor_wall=aw, anchor_mono=am, **extra)

    rs(rt, "route")
    rs(sv, "serve")
    rerouted_id = ""
    t = 1.0
    for i in range(REQTRACE_TRACES):
        tid = f"gatetrace{i:04d}aaaa"
        jid = f"rt-7000-{i:05d}"
        two_hop = i == REQTRACE_TRACES - 1
        if two_hop:
            rerouted_id = tid
        ev(rt, "job_submitted", t, job_id=jid, trace_id=tid,
           tenant="agency", priority=0, queue_depth=1, source="http")
        ev(rt, "request_span", t + 0.01, trace_id=tid, job_id=jid,
           name="route_queue", start=round(am + t, 6),
           end=round(am + t + 0.01, 6))
        ev(rt, "request_span", t + 0.02, trace_id=tid, job_id=jid,
           name="forward", start=round(am + t + 0.01, 6),
           end=round(am + t + 0.02, 6), replica="r0", attempt=1,
           ok=not two_hop)
        fwd = 0.01
        rq = 0.01
        if two_hop:
            ev(rt, "request_span", t + 0.04, trace_id=tid, job_id=jid,
               name="route_queue", start=round(am + t + 0.02, 6),
               end=round(am + t + 0.04, 6))
            ev(rt, "request_span", t + 0.05, trace_id=tid, job_id=jid,
               name="forward", start=round(am + t + 0.04, 6),
               end=round(am + t + 0.05, 6), replica="r1", attempt=2,
               ok=True)
            fwd += 0.01
            rq += 0.02
        ev(rt, "route_decision", t + 0.05, job_id=jid, trace_id=tid,
           tenant="agency", replica="r1" if two_hop else "r0",
           warm=not two_hop, key="gatekey00000000",
           attempt=2 if two_hop else 1)
        # the replica side: admission + exec window
        ev(sv, "job_submitted", t + 0.06, job_id=f"job-7000-{i:05d}",
           trace_id=tid, tenant="agency", priority=0, queue_depth=1,
           source="http")
        ev(sv, "job_start", t + 0.08, job_id=f"job-7000-{i:05d}",
           trace_id=tid, tenant="agency", wait_s=0.02)
        ev(sv, "job_done", t + 0.48, job_id=f"job-7000-{i:05d}",
           trace_id=tid, status="done", wall_s=0.42)
        # terminal relay + request_done: blame is the router partition
        ev(rt, "request_span", t + 0.5, trace_id=tid, job_id=jid,
           name="relay", start=round(am + t + 0.49, 6),
           end=round(am + t + 0.5, 6),
           replica="r1" if two_hop else "r0")
        lat = 0.5
        blame = {
            "route_queue": round(rq, 6), "forward": round(fwd, 6),
            "relay": 0.01,
        }
        blame["replica"] = round(lat - sum(blame.values()), 6)
        ev(rt, "request_done", t + lat, trace_id=tid, job_id=jid,
           status="done", latency_s=lat, tenant="agency",
           hops=2 if two_hop else 1, blame=blame)
        ev(rt, "job_done", t + lat, job_id=jid, trace_id=tid,
           status="done", wall_s=lat)
        # the pinned run scope: a fresh scope per trace in ONE file
        # (the resume-append layout lt_request folds), every event
        # stamped with the trace id
        rs(rn, "xla", dt=t + 0.1, job_id=f"job-7000-{i:05d}",
           trace_id=tid)
        tt = t + 0.1
        for tile in range(REQTRACE_TILES):
            ev(rn, "span", tt + 0.002, name="feed", tile_id=tile,
               start=round(am + tt, 6), end=round(am + tt + 0.002, 6),
               job_id=f"job-7000-{i:05d}", trace_id=tid)
            ev(rn, "tile_start", tt + 0.003, tile_id=tile, attempt=1,
               job_id=f"job-7000-{i:05d}", trace_id=tid)
            ev(rn, "tile_done", tt + 0.015, tile_id=tile, px=400,
               compute_s=0.012, px_per_s=33333.3, feed_backlog=0,
               write_backlog=0, job_id=f"job-7000-{i:05d}",
               trace_id=tid)
            ev(rn, "write_done", tt + 0.017, tile_id=tile, bytes=1024,
               record_s=0.002, job_id=f"job-7000-{i:05d}",
               trace_id=tid)
            tt += 0.018
        ev(rn, "run_done", t + 0.47, status="ok",
           tiles_done=REQTRACE_TILES, pixels=400 * REQTRACE_TILES,
           wall_s=0.37, px_per_s=21621.6, fit_rate=0.8,
           job_id=f"job-7000-{i:05d}", trace_id=tid)
        t += 0.6

    paths = []
    for fname, recs in (
        ("gate_req_router.events.jsonl", rt),
        ("gate_req_serve.events.jsonl", sv),
        ("gate_req_run.events.jsonl", rn),
    ):
        p = str(Path(workdir) / fname)
        with open(p, "w") as f:
            for r in recs:
                f.write(_json.dumps(r, separators=(",", ":")) + "\n")
        paths.append(p)
    return paths, rerouted_id


def run_reqtrace_leg(workdir: str, check) -> None:
    """Request-tracing checks (obs/reqtrace + tools/lt_request).

    Structural, exact: the synthetic fleet streams lint clean (orphan
    lint included), the re-routed request assembles as ONE trace with
    two forward hops on distinct replicas, the blame partition sums to
    the router-observed latency exactly, and a histogram exemplar's
    trace_id resolves to a complete assembled trace.  Banded: assembler
    throughput (the trace leg's 5k ev/s convention) and the emit-path
    cost of trace stamping inside the FLIGHT baseline's documented
    noise band.  Callable on its own (``tests/test_reqtrace.py``)."""
    import time as _time

    from check_events_schema import value_lints

    from land_trendr_tpu.obs.events import EventLog, validate_events_file
    from land_trendr_tpu.obs.metrics import MetricsRegistry
    from land_trendr_tpu.obs.reqtrace import assemble_request

    stream_paths, rerouted_id = _synth_reqtrace_streams(workdir)
    n_events = sum(
        sum(1 for _ in open(p)) for p in stream_paths
    )
    lint_errs = [
        e for p in stream_paths
        for e in validate_events_file(p, extra=value_lints())
    ]
    check(
        "reqtrace.streams_schema_valid", not lint_errs,
        f"{n_events} synthetic fleet events lint clean "
        f"({lint_errs[:2]})",
    )
    t0 = _time.perf_counter()
    rec = assemble_request(stream_paths, rerouted_id)
    assemble_s = _time.perf_counter() - t0
    hops = rec.get("hops", [])
    check(
        "reqtrace.two_hop_structure",
        rec.get("complete") is True and len(hops) == 2
        and hops[0].get("ok") is False and hops[1].get("ok") is True
        and hops[0].get("replica") != hops[1].get("replica"),
        f"re-routed trace {rerouted_id}: {len(hops)} hop(s) "
        f"{[h.get('replica') for h in hops]}, complete="
        f"{rec.get('complete')}",
    )
    check(
        "reqtrace.blame_sums_exact",
        rec.get("latency_s") is not None
        and abs(rec["blame_sum_s"] - rec["latency_s"]) <= 1e-3
        and all(v >= 0 for v in rec["blame"].values()),
        f"blame {rec.get('blame')} sums to {rec.get('blame_sum_s')} vs "
        f"router-observed latency {rec.get('latency_s')}",
    )
    comps = set(rec.get("blame", {}))
    check(
        "reqtrace.blame_components_cross_layer",
        {"forward", "route_queue", "replica_queue", "compute"} <= comps,
        f"components span router AND replica layers: {sorted(comps)}",
    )
    ev_per_s = n_events / assemble_s if assemble_s > 0 else float("inf")
    check(
        "reqtrace.assembler_throughput",
        ev_per_s >= REQTRACE_MIN_EVENTS_PER_S,
        f"assembled across {n_events} events in {assemble_s:.3f}s "
        f"({ev_per_s:,.0f} ev/s vs floor "
        f"{REQTRACE_MIN_EVENTS_PER_S:,})",
    )
    # exemplar → trace loop: the bucket ring's trace_id must assemble
    reg = MetricsRegistry()
    hist = reg.histogram(
        "lt_gate_req_seconds", "g", buckets=(0.1, 1.0, 10.0)
    )
    for i in range(REQTRACE_TRACES):
        hist.observe(0.5, exemplar=f"gatetrace{i:04d}aaaa")
    hist.observe(5.0, exemplar=rerouted_id)  # the tail bucket
    ex = {e["name"]: e["exemplars"] for e in reg.exemplars()}
    tail = (ex.get("lt_gate_req_seconds") or {}).get("10.0") or []
    resolved = (
        assemble_request(stream_paths, tail[-1]["trace_id"])
        if tail else {}
    )
    check(
        "reqtrace.exemplar_resolves_to_trace",
        bool(tail) and resolved.get("complete") is True,
        f"tail-bucket exemplar {tail[-1]['trace_id'] if tail else None} "
        "assembles to a complete cross-layer trace",
    )
    # stamp overhead: the trace context is two extra common fields on
    # the emit path — min-of-reps cost vs the unstamped log must stay
    # inside the flight artifact's documented noise band.  The legs
    # INTERLEAVE (plain, stamped, plain, ...) so container scheduler
    # drift hits both alike, and min-of-reps takes the cost floor
    # (jitter only inflates wall time; a real regression — extra
    # serialization work per emit — inflates the floor itself).
    base = json.loads(FLIGHT_BASELINE.read_text())
    band = float(base["noise_band_pct"])
    reps, n_emit = 5, 3000
    stamp = {"job_id": "job-1-00001", "trace_id": "gatetrace0000aaaa"}
    plain_costs: "list[float]" = []
    stamped_costs: "list[float]" = []
    for r in range(reps):
        for label, common, costs in (
            ("plain", None, plain_costs),
            ("stamped", stamp, stamped_costs),
        ):
            p = str(Path(workdir) / f"stamp_{label}_{r}.jsonl")
            log = EventLog(p, common=common)
            t0 = _time.perf_counter()
            for i in range(n_emit):
                # a production-shaped event (tile_done's field count):
                # the stamping cost is judged against the events that
                # actually dominate a run's stream
                log.emit(
                    "tile_done", tile_id=i, px=400, compute_s=0.012,
                    px_per_s=33333.3, feed_backlog=1, write_backlog=0,
                )
            costs.append(_time.perf_counter() - t0)
            log.close()
    plain, stamped = min(plain_costs), min(stamped_costs)
    delta_us = max(0.0, (stamped - plain) / n_emit * 1e6)
    # the RUN-level claim (the FLIGHT artifact's framing): a tile emits
    # ~7 events (the trace leg's convention), so the stamping cost per
    # tile is delta x 7 — judged against even a FAST 10ms tile, it must
    # sit inside the flight noise band.  (A per-emit ratio would gate
    # json-serializer noise, not the run overhead the band is about.)
    per_tile_pct = 100.0 * (delta_us * 1e-6 * 7) / 0.010
    check(
        "reqtrace.stamp_overhead",
        per_tile_pct <= band,
        f"trace stamping adds {delta_us:.1f}us/emit (min of {reps} "
        f"interleaved reps x {n_emit} tile_done emits) — "
        f"{per_tile_pct:.2f}% of a fast 10ms tile at ~7 events/tile, "
        f"vs the FLIGHT noise band {band}%",
    )


#: fleet-telemetry leg: synthetic pod shape and the bands.  The
#: aggregator floor is an order of magnitude under a cold local
#: measurement (the fold parses 16 small JSON files): it fails an
#: accidentally-quadratic merge, not a noisy container.  The publisher
#: ceiling is min-of-reps (container jitter only inflates the median; a
#: real regression — an O(instruments²) dump, a lock across the write —
#: inflates the cost floor itself).
FLEET_HOSTS = 16
FLEET_MIN_FOLDS_PER_S = 20.0
FLEET_PUBLISH_MAX_MIN_S = 0.05


def _synth_fleet_snaps(directory: str, now: float) -> dict:
    """Write a deterministic FLEET_HOSTS-snapshot set: per-host counters
    with a known sum, one shared histogram, one host stamped stale, one
    torn file.  Returns the expected aggregates."""
    import json as _json
    import os as _os

    from land_trendr_tpu.obs.publish import SNAP_SCHEMA

    tiles_sum = 0
    hist_count = 0
    for i in range(FLEET_HOSTS):
        tiles = 10 * (i + 1)
        tiles_sum += tiles
        hist_count += 3
        stale = i == FLEET_HOSTS - 1
        snap = {
            "schema": SNAP_SCHEMA,
            "kind": "run",
            "host": f"fleet-host-{i:02d}",
            "pid": 1000 + i,
            "generation": 1,
            "seq": 5,
            "t_wall": now - (3600.0 if stale else 1.0),
            "uptime_s": 60.0,
            "interval_s": 5.0,
            "metrics": [
                {"name": "lt_tiles_done_total", "kind": "counter",
                 "help": "t", "labels": {}, "value": float(tiles)},
                {"name": "lt_feed_backlog", "kind": "gauge", "help": "b",
                 "labels": {}, "value": 2.0},
                {"name": "lt_slo_burn_rate", "kind": "gauge", "help": "br",
                 "labels": {}, "value": 0.01 * i},
                {"name": "lt_tile_compute_seconds", "kind": "histogram",
                 "help": "c", "labels": {}, "sum": 3.0, "count": 3,
                 "bounds": [0.1, 1.0, 10.0], "buckets": [1, 1, 1, 0]},
            ],
            "state": {"progress": {"phase": "pipeline", "tiles_done": tiles}},
        }
        p = _os.path.join(directory, f"fleet-host-{i:02d}.1000.snap.json")
        # synthetic aggregator fixtures, not durable artifacts: the very
        # next block plants a deliberately TORN sibling, so the pair
        # stays plain writes
        with open(p, "w") as f:  # lt: noqa[LT012]
            f.write(_json.dumps(snap, separators=(",", ":")))
        # mtime pinned to the snapshot's own stamp: staleness is judged
        # on the FRESHER of t_wall and the shared-FS mtime, and the
        # synthetic `now` is decoupled from the real clock
        _os.utime(p, (snap["t_wall"], snap["t_wall"]))
    # lt: noqa[LT012] — a torn snapshot IS the fixture: the aggregator
    # leg asserts it is flagged corrupt without crashing the fold
    with open(_os.path.join(directory, "torn-host.999.snap.json"), "w") as f:
        f.write('{"schema": 1, "host": "torn-host", "pid": 999, "t_wa')
    return {
        "tiles_sum": float(tiles_sum),
        "backlog_sum": 2.0 * FLEET_HOSTS,
        "burn_max": 0.01 * (FLEET_HOSTS - 1),
        "hist_count": hist_count,
    }


def run_fleet_leg(workdir: str, check) -> None:
    """Fleet-telemetry plane checks (obs publish/aggregate/history/alerts).

    Structural, exact: the pod fold's counters equal the per-host sums,
    gauges follow the merge-policy table, the stale host and the torn
    snapshot are flagged (never silently dropped, never a crash), two
    folds render byte-identical exposition, and a scripted history
    drives a firing → resolved alert lifecycle deterministically.
    Banded: aggregator fold throughput and publisher min-of-reps
    snapshot cost.  Callable on its own (``tests/test_fleet.py``) — it
    needs no bench baselines.
    """
    import time as _time

    from land_trendr_tpu.obs import aggregate
    from land_trendr_tpu.obs.alerts import AlertEngine, AlertRule
    from land_trendr_tpu.obs.metrics import MetricsRegistry
    from land_trendr_tpu.obs.publish import TelemetryPublisher

    snap_dir = str(Path(workdir) / "fleet_snaps")
    Path(snap_dir).mkdir(parents=True, exist_ok=True)
    now = 1.8e9
    expect = _synth_fleet_snaps(snap_dir, now)

    view = aggregate.fold_dir(snap_dir, now=now)
    by_name = {
        m["name"]: m for m in view["metrics"] if not m.get("labels")
    }
    check(
        "fleet.counters_sum_exact",
        by_name.get("lt_tiles_done_total", {}).get("value")
        == expect["tiles_sum"],
        f"pod lt_tiles_done_total "
        f"{by_name.get('lt_tiles_done_total', {}).get('value')} == "
        f"per-host sum {expect['tiles_sum']}",
    )
    check(
        "fleet.gauge_policy",
        by_name.get("lt_feed_backlog", {}).get("value")
        == expect["backlog_sum"]
        and abs(
            (by_name.get("lt_slo_burn_rate", {}).get("value") or 0)
            - expect["burn_max"]
        ) < 1e-9,
        f"backlog sums to {expect['backlog_sum']}, burn rate takes the "
        f"pod max {expect['burn_max']}",
    )
    hist = by_name.get("lt_tile_compute_seconds", {})
    check(
        "fleet.histogram_merge",
        hist.get("count") == expect["hist_count"]
        and hist.get("buckets") == [FLEET_HOSTS, FLEET_HOSTS, FLEET_HOSTS, 0],
        f"merged histogram count {hist.get('count')} buckets "
        f"{hist.get('buckets')}",
    )
    counts = view["counts"]
    check(
        "fleet.staleness_flagged",
        counts["stale"] == 1 and counts["corrupt"] == 1
        and counts["folded"] == FLEET_HOSTS
        and len(view["hosts"]) == FLEET_HOSTS + 1,
        f"{counts['stale']} stale + {counts['corrupt']} torn flagged, "
        f"all {FLEET_HOSTS + 1} files listed, none dropped silently",
    )
    prom_a = aggregate.render_prom(view)
    prom_b = aggregate.render_prom(aggregate.fold_dir(snap_dir, now=now))
    check(
        "fleet.byte_stable",
        prom_a == prom_b and len(prom_a) > 0,
        f"two independent folds render identical exposition "
        f"({len(prom_a)} bytes)",
    )

    reps = 20
    t0 = _time.perf_counter()
    for _ in range(reps):
        aggregate.fold_dir(snap_dir, now=now)
    folds_per_s = reps / (_time.perf_counter() - t0)
    check(
        "fleet.aggregator_throughput",
        folds_per_s >= FLEET_MIN_FOLDS_PER_S,
        f"{folds_per_s:,.0f} folds/s of a {FLEET_HOSTS}-host set vs "
        f"floor {FLEET_MIN_FOLDS_PER_S:,.0f}",
    )

    # alert lifecycle on a SCRIPTED history: deterministic and replayable
    rule = AlertRule(
        name="gate_queue", kind="threshold", metric="q", op=">",
        value=5.0, for_s=2.0, hold_down_s=3.0,
    )

    def _script() -> list:
        eng = AlertEngine((rule,))
        out = []
        for t in range(20):
            q = 10.0 if 4 <= t < 9 else 0.0
            for tr in eng.evaluate(
                [{"t": float(t), "metrics": {"q": q}}], float(t)
            ):
                out.append((t, tr["state"], tr["duration_s"]))
        return out

    run1, run2 = _script(), _script()
    check(
        "fleet.alert_deterministic",
        run1 == run2
        and [(t, s) for t, s, _ in run1] == [(6, "firing"), (12, "resolved")]
        and all(d >= 0 for _, _, d in run1),
        f"scripted history replays to identical transitions: {run1}",
    )

    # publisher overhead, min-of-reps: a populated registry snapshots +
    # writes atomically well under the ceiling
    reg = MetricsRegistry()
    for i in range(40):
        reg.counter(f"lt_gate_counter_{i}", "g").inc(i)
        reg.gauge(f"lt_gate_gauge_{i}", "g").set(i)
    for i in range(8):
        reg.histogram(f"lt_gate_hist_{i}", "g").observe(0.5)
    pub = TelemetryPublisher(
        str(Path(workdir) / "fleet_pub"), reg, interval_s=5.0,
        host="gate-pub",
    )
    costs = []
    for _ in range(10):
        t0 = _time.perf_counter()
        pub.publish_now()
        costs.append(_time.perf_counter() - t0)
    check(
        "fleet.publisher_overhead",
        min(costs) <= FLEET_PUBLISH_MAX_MIN_S,
        f"min-of-reps publish {min(costs) * 1e3:.2f}ms vs ceiling "
        f"{FLEET_PUBLISH_MAX_MIN_S * 1e3:.0f}ms (median "
        f"{sorted(costs)[len(costs) // 2] * 1e3:.2f}ms)",
    )


def run_scheduler_leg(workdir: str, check) -> None:
    """Elastic-scheduler leg: an injected slow-host two-process pod run
    twice — static ``host_share`` split vs the shared-manifest lease
    queue with speculation (``tools/elastic_soak.slow_host_leg``) —
    asserting the structural invariants exactly (no lost tile, no
    double-counted done id, at least one speculative win) and the
    analytics directionally (pod busy-union idle gap and
    ``host_imbalance`` collapse vs the static baseline, via the
    ``lt_trace`` fold)."""
    import elastic_soak

    n_tiles = (120 // 20) ** 2
    try:
        res = elastic_soak.slow_host_leg(
            Path(workdir) / "scheduler", size=120, tile=20, verbose=False
        )
    except AssertionError as e:
        # the leg's own invariant assertions ARE the gate's findings
        check("scheduler.invariants", False, str(e))
        return
    except Exception as e:
        check("scheduler.ran", False, f"slow-host pod soak raised: {e}")
        return
    st, el = res["static"], res["elastic"]
    for mode, r in (("static", st), ("elastic", el)):
        check(
            f"scheduler.{mode}_no_lost_tiles",
            r["unique_done_tiles"] == n_tiles,
            f"{r['unique_done_tiles']} unique done tiles of {n_tiles}",
        )
    check(
        "scheduler.no_double_count",
        el["duplicate_done_records"]
        <= el["tiles_speculated"] + el["tiles_stolen"],
        f"{el['duplicate_done_records']} duplicate done record(s) vs "
        f"{el['tiles_speculated']} speculated + {el['tiles_stolen']} "
        "stolen (duplicates can only come from speculation/steal races)",
    )
    check(
        "scheduler.idle_gap_collapse",
        el["idle_gap_pod_s"] < st["idle_gap_pod_s"],
        f"pod busy-union idle gap {el['idle_gap_pod_s']}s elastic vs "
        f"{st['idle_gap_pod_s']}s static",
    )
    check(
        "scheduler.imbalance_collapse",
        bool(
            st["host_imbalance"] and el["host_imbalance"]
            and el["host_imbalance"] < st["host_imbalance"]
        ),
        f"host_imbalance {el['host_imbalance']} elastic vs "
        f"{st['host_imbalance']} static",
    )
    check(
        "scheduler.speculative_win",
        el["spec_wins"] >= 1,
        f"{el['spec_wins']} speculative win(s), "
        f"{el['tiles_speculated']} speculated",
    )


def run_router_leg(workdir: str, check) -> None:
    """Serving-fleet router leg (land_trendr_tpu/fleet +
    tools/fleet_bench): replay the heavy-tailed multi-tenant trace
    through 1 vs N spawned replicas and gate the EXACT invariants —
    warm-affinity hit ratio strictly above the no-affinity baseline,
    zero lost jobs across a replica SIGKILL (at least one job
    re-routed), artifacts byte-identical for the same spec across all
    legs — plus the reported p99s for the record.  Minutes-scale (seven
    jax replica processes), so the tier-1 smoke passes
    ``--skip-router``; CLI gate runs carry the leg."""
    import fleet_bench

    out = str(Path(workdir) / "fleet_smoke.json")
    if fleet_bench.main(["--smoke", "--out", out]) not in (0, 1):
        check("router.ran", False, "fleet_bench --smoke errored")
        return
    got = json.loads(Path(out).read_text())
    legs = got.get("legs", {})
    inv = got.get("invariants", {})
    aff = legs.get("affinity", {})
    noaff = legs.get("noaff", {})
    kill = legs.get("kill", {})
    check(
        "router.warm_affinity_above_baseline",
        inv.get("affinity_warm_above_noaff") is True,
        f"warm-hit ratio {aff.get('warm_hit_ratio')} (affinity) vs "
        f"{noaff.get('warm_hit_ratio')} (no-affinity baseline)",
    )
    check(
        "router.zero_lost_jobs_across_kill",
        inv.get("zero_lost_jobs_across_kill") is True,
        f"replica {kill.get('killed_replica')} SIGKILLed mid-trace: "
        f"{kill.get('lost_jobs')} lost, {kill.get('rerouted_jobs')} "
        "re-routed to completion",
    )
    check(
        "router.parity_across_legs",
        inv.get("parity_across_legs") is True,
        "same-spec artifacts byte-identical across single/noaff/"
        "affinity/kill legs",
    )
    check(
        "router.no_leg_lost_jobs",
        inv.get("no_leg_lost_jobs") is True,
        f"p99 latency: single {legs.get('single', {}).get('p99_latency_s')}s, "
        f"no-affinity {noaff.get('p99_latency_s')}s, "
        f"affinity {aff.get('p99_latency_s')}s",
    )


def run_tune_leg(workdir: str, check) -> None:
    """Autotuner leg (land_trendr_tpu/tune + tools/tune_bench).

    Structural, exact: a probed profile round-trips through the store
    byte-stably, a warm store serves it with ZERO probes and identical
    knob values, ``"auto"`` resolution is deterministic, and the
    tuned-vs-default end-to-end runs produce byte-identical artifacts
    (the tuned knobs are pure execution facts) with the run's
    ``tune_profile`` event reporting the zero-probe store hit.  Banded:
    tuned must be ≥ default on at least one probe group — guaranteed by
    construction (every candidate set contains the default), so a FAIL
    here means the probe search itself regressed.  Callable on its own
    (``tests/test_tune.py``) — it needs no bench baselines."""
    import tune_bench

    out = str(Path(workdir) / "tune_smoke.json")
    if tune_bench.main(["--smoke", "--out", out]) not in (0, 1):
        check("tune.ran", False, "tune_bench --smoke errored")
        return
    got = json.loads(Path(out).read_text())
    inv = got.get("invariants", {})
    check(
        "tune.profile_roundtrip_stable",
        inv.get("profile_roundtrip_byte_stable") is True,
        "store save -> load -> save is byte-identical",
    )
    check(
        "tune.warm_zero_probes",
        inv.get("warm_zero_probes") is True
        and inv.get("warm_identical_knobs") is True,
        "second autotune served from the store: zero probes, identical "
        "knob values",
    )
    check(
        "tune.resolution_deterministic",
        inv.get("resolution_deterministic") is True,
        "two 'auto' resolutions of one key give identical RunConfigs",
    )
    check(
        "tune.parity",
        inv.get("artifacts_byte_identical") is True
        and inv.get("run_tune_profile_event") is True,
        "tuned-profile run artifacts ≡ default run; stream carries the "
        "probes=0 store verdict",
    )
    sp = got.get("max_group_speedup")
    check(
        "tune.group_win",
        inv.get("tuned_never_worse_than_default") is True
        and inv.get("all_groups_probed") is True
        and sp is not None and sp >= 1.0,
        f"tuned ≥ default on every probed group (best group speedup "
        f"{sp})",
    )


#: capacity-planner leg: the scripted decision history (a seeded drive
#: of the live pure machines — no fleet processes) and the replay
#: bands.  The ≥100x throughput floor is the acceptance bound the
#: capacity artifact documents; the replayer re-derives decisions at
#: CPU iteration speed, so the bound is loose by orders of magnitude —
#: it fails a replayer that started doing real-time waits, not a noisy
#: container.
CAPACITY_SCRIPT_SEED = 23
CAPACITY_SCRIPT_EVENTS = 1500
CAPACITY_MIN_SPEEDUP_X = 100.0


def run_capacity_leg(workdir: str, check) -> None:
    """Capacity-planner checks (fleet/capacity + the committed curve).

    Structural, exact: a seeded scripted decision history replays
    byte-identically through fresh pure machines (every recorded
    pick/choose/remove/autoscale output re-derived and matched), a
    tampered copy of the same history is DETECTED (the equivalence
    check is falsifiable, not a tautology), and the committed
    ``CAPACITY_r17.json`` passes the exact report schema with >= 3
    replica counts and a named knee blame per curve.  Banded: replay
    throughput >= 100x the recorded span.  Callable on its own
    (``tests/test_capacity.py``) — it needs no fleet processes."""
    from land_trendr_tpu.fleet.capacity import (
        replay_decisions,
        validate_report,
        write_scripted_history,
    )
    from land_trendr_tpu.obs.reqtrace import BLAME_PRIORITY

    hist = str(Path(workdir) / "capacity_scripted.decisions.jsonl")
    script = write_scripted_history(
        hist, seed=CAPACITY_SCRIPT_SEED, events=CAPACITY_SCRIPT_EVENTS
    )
    rep = replay_decisions(hist)
    check(
        "capacity.scripted_replay_match",
        rep.match and rep.mismatch_seq is None,
        f"{rep.matched}/{rep.decisions} decisions replayed "
        f"byte-identically over a {script['span_s']:.1f}s recorded span "
        f"(first mismatch seq {rep.mismatch_seq})",
    )
    check(
        "capacity.replay_throughput",
        rep.match and rep.speedup_x >= CAPACITY_MIN_SPEEDUP_X,
        f"replayed a {rep.recorded_span_s:.1f}s span in "
        f"{rep.replay_wall_s * 1e3:.1f}ms ({rep.speedup_x:,.0f}x vs "
        f"floor {CAPACITY_MIN_SPEEDUP_X:.0f}x)",
    )
    # falsifiability: flip one recorded output and the replay must
    # notice — a replayer that echoes the log would pass the match
    # check vacuously
    tampered = str(Path(workdir) / "capacity_tampered.decisions.jsonl")
    lines = Path(hist).read_text().splitlines()
    flipped = False
    for i, line in enumerate(lines):
        rec = json.loads(line)
        if rec.get("kind") == "pick":
            rec["job_id"] = rec["job_id"] + "-tampered"
            lines[i] = json.dumps(rec, sort_keys=True)
            flipped = True
            break
    Path(tampered).write_text("\n".join(lines) + "\n")
    trep = replay_decisions(tampered) if flipped else None
    check(
        "capacity.tamper_detected",
        flipped and trep is not None and not trep.match
        and trep.mismatch_seq is not None
        and trep.mismatch is not None and trep.mismatch["kind"] == "pick",
        f"one flipped pick output caught at seq "
        f"{trep.mismatch_seq if trep else None}",
    )
    base = json.loads(CAPACITY_BASELINE.read_text())
    errs = validate_report(base)
    curves = base.get("curves") or []
    counts = sorted(
        c.get("replicas") for c in curves if isinstance(c, dict)
    )
    check(
        "capacity.curve_schema",
        not errs and len(counts) >= 3 and len(set(counts)) == len(counts),
        f"committed curve valid for replica counts {counts} "
        f"({errs[:2]})",
    )
    vocab = (*BLAME_PRIORITY, "other")
    knees = [
        next(
            (p.get("knee_blame") for p in c.get("points", [])
             if p.get("knee")),
            None,
        )
        for c in curves
    ]
    check(
        "capacity.knees_named",
        bool(knees) and all(b in vocab for b in knees),
        f"every committed curve names its knee blame: {knees}",
    )
    crep = base.get("replay") or {}
    srep = base.get("scripted_replay") or {}
    check(
        "capacity.committed_replay_stands",
        crep.get("match") is True and srep.get("match") is True
        and float(srep.get("speedup_x", 0)) >= CAPACITY_MIN_SPEEDUP_X,
        f"committed artifact's live replay {crep.get('matched')}/"
        f"{crep.get('decisions')} matched; scripted at "
        f"{srep.get('speedup_x')}x",
    )


def run_recovery_leg(workdir: str, check) -> None:
    """Crash-safe control plane leg (fleet/journal + router recovery).

    Structural, exact: the admission journal folds byte-stably across
    close/reopen, prefix compaction never drops a live job, a torn tail
    is GC'd without losing a committed record, and a router recovered
    from a fabricated crash journal (forwarded to a dead replica base)
    requeues the in-flight job and finishes it with artifacts
    byte-identical to a clean routed run.  Banded: min-of-reps
    per-append commit wall under ``JOURNAL_APPEND_MAX_MS``.  In-process
    (one serve replica on a thread) — seconds-scale, so the tier-1
    smoke runs it."""
    import hashlib
    import threading
    import time as _time

    import numpy as _np

    from land_trendr_tpu.fleet import FleetRouter, RouterConfig
    from land_trendr_tpu.fleet.journal import AdmissionJournal
    from land_trendr_tpu.io.synthetic import (
        SceneSpec,
        make_stack,
        write_stack,
    )
    from land_trendr_tpu.serve import SegmentationServer, ServeConfig

    # -- journal fold stability / compaction / torn tail ------------------
    jroot = str(Path(workdir) / "recovery_journal")
    j = AdmissionJournal(jroot, segment_bytes=64 * 1024)
    for i in range(300):
        jid = f"j{i:04d}"
        j.append("admitted", jid, payload={"n": i}, t=float(i))
        j.append(
            "forwarded", jid,
            replica_base="http://127.0.0.1:9", replica_job_id=jid,
        )
        if i < 250:
            j.append("terminal", jid, state="done", t=float(i))
    first = j.replay()
    j.close()
    j = AdmissionJournal(jroot, segment_bytes=64 * 1024)
    second = j.replay()
    check(
        "recovery.replay_stable",
        json.dumps(first, sort_keys=True)
        == json.dumps(second, sort_keys=True),
        f"{len(second)} folded job(s) identical across close/reopen",
    )
    live = {
        jid for jid, st in second.items() if st["status"] != "terminal"
    }
    dropped = j.compact()
    after = j.replay()
    check(
        "recovery.compaction_safe",
        live <= set(after)
        and all(after[jid]["status"] != "terminal" for jid in live),
        f"{dropped} fully-terminal segment(s) dropped; all {len(live)} "
        "live job(s) survive the compaction",
    )
    j.close()
    segs = sorted(Path(jroot).glob("seg-*.jsonl"))
    with open(segs[-1], "ab") as f:
        f.write(b'{"rec":"admitted","job_id":"torn-')  # mid-crash tear
    j = AdmissionJournal(jroot, segment_bytes=64 * 1024)
    third = j.replay()
    check(
        "recovery.torn_tail_dropped",
        json.dumps(after, sort_keys=True)
        == json.dumps(third, sort_keys=True)
        and "torn-" not in third,
        "half-written final line dropped at reopen, committed records "
        "untouched",
    )
    # -- per-append overhead (min-of-reps: scheduler noise filtered) ------
    reps = []
    for _ in range(5):
        t0 = _time.perf_counter()
        for i in range(50):
            j.append("terminal", f"bench{i}", state="done", t=0.0)
        reps.append((_time.perf_counter() - t0) / 50)
    j.close()
    per_ms = min(reps) * 1e3
    check(
        "recovery.append_overhead",
        per_ms < JOURNAL_APPEND_MAX_MS,
        f"min-of-reps journal append {per_ms:.3f}ms vs "
        f"{JOURNAL_APPEND_MAX_MS}ms bound",
    )

    # -- recovered-vs-clean artifact parity -------------------------------
    def digest(wd: str) -> dict:
        out: dict = {}
        for p in sorted(Path(wd).glob("tile_*.npz")):
            with _np.load(p) as z:
                out[p.name] = {
                    name: hashlib.sha256(
                        _np.ascontiguousarray(z[name]).tobytes()
                    ).hexdigest()
                    for name in sorted(z.files)
                }
        return out

    stack_dir = str(Path(workdir) / "recovery_stack")
    write_stack(
        stack_dir,
        make_stack(SceneSpec(
            width=48, height=40, year_start=1990, year_end=2013, seed=11,
        )),
    )
    job = {
        "stack_dir": stack_dir,
        "tile_size": 20,
        "params": {"max_segments": 4, "vertex_count_overshoot": 2},
        "run_overrides": {"retry_backoff_s": 0.0},
    }
    server = SegmentationServer(ServeConfig(
        workdir=str(Path(workdir) / "recovery_replica"), feed_cache_mb=64,
    ))
    srv_thread = threading.Thread(target=server.serve_forever)
    srv_thread.start()

    def routed(rt_dir: str, submit: "dict | None", jid: "str | None"):
        router = FleetRouter(RouterConfig(
            workdir=rt_dir,
            replicas=(f"http://127.0.0.1:{server.port}",),
            health_interval_s=0.2,
        ))
        rt_thread = threading.Thread(target=router.serve_forever)
        rt_thread.start()
        try:
            if submit is not None:
                jid = router.submit(submit)["job_id"]
            deadline = _time.monotonic() + 300
            while _time.monotonic() < deadline:
                s = router.job_status(jid)
                if s["state"] not in ("queued", "routed"):
                    break
                _time.sleep(0.1)
            return s, router.recovery
        finally:
            router.stop()
            rt_thread.join(timeout=300)

    try:
        clean_s, _ = routed(
            str(Path(workdir) / "recovery_router_clean"), dict(job), None
        )
        rt_crash = Path(workdir) / "recovery_router_crash"
        jwd = str(Path(workdir) / "recovery_job_wd")
        jid = "rt-0-00001"
        payload = dict(job)
        payload["workdir"] = jwd
        payload["out_dir"] = jwd + "_o"
        (rt_crash / "journal").mkdir(parents=True)
        (rt_crash / "journal" / "seg-00000001.jsonl").write_text(
            json.dumps({
                "rec": "admitted", "job_id": jid, "payload": payload,
                "tenant": "gate", "priority": 0, "key": "gate-key",
                "trace_id": "gaterecover00001", "workdir": jwd,
                "out_dir": jwd + "_o", "source": "http", "t": 0.0,
            }) + "\n" + json.dumps({
                "rec": "forwarded", "job_id": jid,
                "replica_base": "http://127.0.0.1:9",
                "replica_job_id": "gone-1", "t": 0.0,
            }) + "\n"
        )
        rec_s, recovery = routed(str(rt_crash), None, jid)
    finally:
        server.stop()
        srv_thread.join(timeout=120)
    check(
        "recovery.replayed_job_completes",
        clean_s["state"] == "done" and rec_s["state"] == "done"
        and recovery is not None and recovery.get("replayed") == 1
        and recovery.get("requeued") == 1,
        f"clean {clean_s['state']}, recovered {rec_s['state']} "
        f"(recovery {recovery})",
    )
    check(
        "recovery.artifact_parity",
        clean_s["state"] == "done" and rec_s["state"] == "done"
        and digest(clean_s["workdir"]) == digest(jwd)
        and len(digest(jwd)) > 0,
        "recovered job's artifacts byte-identical to the clean routed "
        "run",
    )


def run_lint_leg(workdir: str, check) -> None:
    """lt-lint leg: the tree must be clean (zero unbaselined findings)
    and the full twelve-rule run must stay inside its wall-time budget.

    Both checks are structural, not banded: a finding that is neither
    noqa'd nor baselined-with-a-reason is a regression exactly like a
    failed parity flag, and a run that blows ``LINT_BUDGET_S`` means an
    interprocedural pass went quadratic — the same gate tier-1 applies
    via ``tests/test_lint.py::test_repo_tree_is_clean``, enforced here
    too so a perf-gate-only CI lane cannot ship lint drift."""
    import subprocess
    import time as _time

    from lt_lint import LINT_BUDGET_S

    t0 = _time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lt_lint.py"), "--json"],
        capture_output=True, text=True, cwd=str(REPO),
        timeout=LINT_BUDGET_S * 4,
    )
    elapsed = _time.monotonic() - t0
    try:
        report = json.loads(proc.stdout)
    except json.JSONDecodeError:
        check(
            "lint.clean", False,
            f"lt_lint --json exited {proc.returncode} with unparseable "
            f"output: {proc.stderr.strip()[:200]}",
        )
        return
    findings = report.get("findings", [])
    check(
        "lint.clean",
        proc.returncode == 0 and report.get("clean") is True and not findings,
        f"{len(findings)} unbaselined finding(s) over "
        f"{report.get('files_checked')} files "
        f"({len(report.get('baselined', []))} baselined, "
        f"{report.get('noqa_suppressed')} noqa-suppressed)",
    )
    check(
        "lint.budget",
        elapsed < LINT_BUDGET_S,
        f"full twelve-rule run took {elapsed:.1f}s vs "
        f"{LINT_BUDGET_S:.0f}s budget",
    )


def run_gate(
    workdir: str, checks: list, scheduler: bool = True, router: bool = True
) -> None:
    """Run the bench smokes + the trace-assembly leg; append
    (name, ok, detail) rows.  ``scheduler=False`` skips the elastic
    scheduler leg (two 2-process jax pods, minutes-scale — the tier-1
    smoke test skips it; the lease invariants stay tier-1-covered by
    ``tests/test_leases.py`` and ``fault_soak``'s lease case);
    ``router=False`` likewise skips the fleet-router leg (seven jax
    replica processes; tier-1 covers the same invariants in-process via
    ``tests/test_fleet_serve.py``)."""
    import batch_bench
    import feed_bench
    import fetch_bench
    import flight_overhead
    import serve_bench
    import upload_bench

    def check(name: str, ok: bool, detail: str) -> None:
        checks.append({"check": name, "ok": bool(ok), "detail": detail})

    # -- feed (RAM decode cache) ------------------------------------------
    base = json.loads(FEED_BASELINE.read_text())
    out = str(Path(workdir) / "feed_smoke.json")
    if feed_bench.main(["--smoke", "--out", out]) != 0:
        check("feed.ran", False, "feed_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check("feed.parity", got.get("parity_ok") is True, "cached reads byte-identical")
        base_hr = _hit_rate(base["cache_stats"]) or 0.0
        got_hr = _hit_rate(got["cache_stats"]) or 0.0
        band = base_hr * 0.5
        check(
            "feed.hit_rate",
            got_hr >= band,
            f"smoke hit rate {got_hr:.3f} vs band {band:.3f} "
            f"(committed {base_hr:.3f})",
        )
        check(
            "feed.cache_hits",
            got["cache_stats"].get("hits", 0) > 0,
            "cache served at least one revisit",
        )

    # -- fetch (packed device→host) ---------------------------------------
    base = json.loads(FETCH_BASELINE.read_text())
    out = str(Path(workdir) / "fetch_smoke.json")
    if fetch_bench.main(["--smoke", "--out", out]) != 0:
        check("fetch.ran", False, "fetch_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check("fetch.parity", got["parity"]["ok"] is True, "packed ≡ per-product")
        check(
            "fetch.transfers_per_tile",
            got["workload"]["transfers_per_tile_packed"] == 1,
            "packed fetch is one transfer per tile",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_packed_sync"] * RATIO_BAND)
        sp = max(got["speedup_packed_sync"], got["speedup_packed_async"])
        check(
            "fetch.speedup",
            sp >= band,
            f"smoke speedup {sp:.2f} vs band {band:.2f} "
            f"(committed {base['speedup_packed_sync']:.2f})",
        )

    # -- upload (packed host→device) + ingest store -----------------------
    base = json.loads(UPLOAD_BASELINE.read_text())
    out = str(Path(workdir) / "upload_smoke.json")
    if upload_bench.main(["--smoke", "--out", out]) != 0:
        check("upload.ran", False, "upload_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check("upload.parity", got["parity"]["ok"] is True, "unpacked ≡ fed arrays")
        check(
            "upload.transfers_per_tile",
            got["workload"]["transfers_per_tile_packed"] == 1,
            "packed upload is one transfer per tile",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_packed_sync"] * RATIO_BAND)
        sp = max(got["speedup_packed_sync"], got["speedup_packed_async"])
        check(
            "upload.speedup",
            sp >= band,
            f"smoke speedup {sp:.2f} vs band {band:.2f} "
            f"(committed {base['speedup_packed_sync']:.2f})",
        )
        store = got.get("ingest_store")
        if store is None:
            check("store.ran", False, "smoke skipped the ingest-store phase")
        else:
            check(
                "store.parity", store["parity_ok"] is True,
                "store-served window reads byte-identical",
            )
            # structural, not a noisy wall ratio: the warm/restart passes
            # must skip TIFF decode entirely (the acceptance invariant)
            for leg in ("store_warm", "store_restart"):
                check(
                    f"store.{leg}_decode_skipped",
                    store[leg]["stats"]["misses"] == 0
                    and store[leg]["hit_rate"] is not None
                    and store[leg]["hit_rate"] >= 0.99,
                    f"{leg}: hit rate {store[leg]['hit_rate']} with "
                    f"{store[leg]['stats']['misses']} misses",
                )

    # -- serve (warm program cache + shared ingest store) -----------------
    base = json.loads(SERVE_BASELINE.read_text())
    out = str(Path(workdir) / "serve_smoke.json")
    if serve_bench.main(["--smoke", "--out", out]) != 0:
        check("serve.ran", False, "serve_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check(
            "serve.parity", got["parity_ok"] is True,
            "warm job artifacts ≡ cold job artifacts",
        )
        # THE structural acceptance invariant: a warm job submitted to a
        # running server performs zero jit compiles (program-cache hit)
        # and zero TIFF decodes (every block store-served) — exact, not
        # a noisy wall ratio
        inv = got["invariants"]
        check(
            "serve.warm_zero_compiles",
            inv["warm_zero_compiles"] is True,
            f"warm program_cache: {got['warm']['program_cache']}",
        )
        check(
            "serve.warm_zero_decodes",
            inv["warm_zero_decodes"] is True,
            f"warm ingest_store: {got['warm']['ingest_store']}",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_warm"] * RATIO_BAND)
        check(
            "serve.warm_speedup",
            got["speedup_warm"] is not None
            and got["speedup_warm"] >= band,
            f"smoke warm speedup {got['speedup_warm']} vs band "
            f"{band:.2f} (committed {base['speedup_warm']})",
        )

    # -- cross-job continuous batching (shared launches) ------------------
    base = json.loads(BATCH_BASELINE.read_text())
    out = str(Path(workdir) / "batch_smoke.json")
    if batch_bench.main(["--smoke", "--out", out]) != 0:
        check("batch.ran", False, "batch_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check(
            "batch.parity", got["parity_ok"] is True,
            "every job's artifacts ≡ the one-run-per-job reference, "
            "batched or not",
        )
        # structural, exact: the flood coalesces (>1 job per launch),
        # the batch=False leg never emits a launch, and nothing is
        # rejected/failed — packing must never cost admission or jobs
        inv = got["invariants"]
        check(
            "batch.coalesced",
            inv["batched_coalesces"] is True
            and inv["base_never_batches"] is True
            and inv["all_done"] is True,
            f"{got['batched']['launches']} launch(es), "
            f"{got['batched']['jobs_per_launch']} jobs/launch over "
            f"{got['workload']['jobs']} jobs",
        )
        check(
            "batch.occupancy",
            (got["batched"]["occupancy"] or 0) >= BATCH_OCCUPANCY_FLOOR,
            f"padded-px occupancy {got['batched']['occupancy']} vs "
            f"floor {BATCH_OCCUPANCY_FLOOR}",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_batched"] * RATIO_BAND)
        check(
            "batch.speedup",
            got["speedup_batched"] is not None
            and got["speedup_batched"] >= band,
            f"smoke batched speedup {got['speedup_batched']} vs band "
            f"{band:.2f} (committed {base['speedup_batched']})",
        )

    run_trace_leg(workdir, check)
    run_reqtrace_leg(workdir, check)
    run_fleet_leg(workdir, check)
    run_tune_leg(workdir, check)
    run_capacity_leg(workdir, check)
    # unconditional: in-process and seconds-scale, unlike the
    # multi-process scheduler/router legs below
    run_recovery_leg(workdir, check)
    if scheduler:
        run_scheduler_leg(workdir, check)
    if router:
        run_router_leg(workdir, check)

    # -- flight recorder (ring + sampler overhead) ------------------------
    base = json.loads(FLIGHT_BASELINE.read_text())
    out = str(Path(workdir) / "flight_smoke.json")
    try:
        got = flight_overhead.run_bench(smoke=True, out_path=out)
    except Exception as e:
        check("flight.ran", False, f"flight_overhead smoke raised: {e}")
    else:
        # structural, exact: the on-run's ring dump is a schema-valid
        # events slice and the sampler series is non-empty
        fl = got.get("flight", {})
        check(
            "flight.dump_valid",
            fl.get("dump_valid") is True,
            f"flight.jsonl schema-valid ({fl.get('dump_errors')})",
        )
        check(
            "flight.sampler_fired",
            fl.get("samples", 0) >= 1,
            f"{fl.get('samples', 0)} flight_sample events in the dump",
        )
        # the documented noise band from the committed artifact, checked
        # on the MIN-of-reps overhead (container jitter only inflates
        # wall time; a real regression — a lock on the emit path, an
        # O(n) ring scan — inflates the cost floor itself)
        band = float(base["noise_band_pct"])
        check(
            "flight.overhead",
            got["overhead_min_pct"] <= band,
            f"smoke min-rep overhead {got['overhead_min_pct']}% (median "
            f"{got['overhead_pct']}%) vs documented noise band {band}% "
            f"(committed {base['overhead_min_pct']}%)",
        )

    # LAST on purpose: the lint subprocess is ~12s of pure CPU churn,
    # and the flight leg's overhead micro-bench must not inherit a
    # warm-throttled cgroup from it
    run_lint_leg(workdir, check)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable verdict only")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the smoke artifacts under DIR")
    ap.add_argument("--skip-scheduler", action="store_true",
                    help="skip the elastic scheduler leg (two 2-process "
                    "jax pods, minutes-scale; the tier-1 smoke test "
                    "passes this — CLI gate runs carry the leg)")
    ap.add_argument("--skip-router", action="store_true",
                    help="skip the serving-fleet router leg (seven jax "
                    "replica processes, minutes-scale; the tier-1 smoke "
                    "test passes this — CLI gate runs carry the leg)")
    args = ap.parse_args(argv)

    for p in (FEED_BASELINE, FETCH_BASELINE, UPLOAD_BASELINE,
              SERVE_BASELINE, FLIGHT_BASELINE, CAPACITY_BASELINE,
              BATCH_BASELINE):
        if not p.exists():
            print(f"error: committed baseline {p.name} missing", file=sys.stderr)
            return 2

    workdir = args.keep or tempfile.mkdtemp(prefix="lt_perf_gate_")
    Path(workdir).mkdir(parents=True, exist_ok=True)
    checks: list = []
    try:
        run_gate(
            workdir, checks,
            scheduler=not args.skip_scheduler,
            router=not args.skip_router,
        )
    finally:
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)

    failed = [c for c in checks if not c["ok"]]
    verdict = {"ok": not failed, "checks": checks}
    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        for c in checks:
            print(f"  {'ok  ' if c['ok'] else 'FAIL'} {c['check']}: {c['detail']}")
        print(json.dumps({"ok": verdict["ok"], "checks": len(checks),
                          "failed": len(failed)}))
    if failed and not args.json:
        for c in failed:
            print(f"regression: {c['check']}: {c['detail']}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
