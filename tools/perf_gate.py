"""Perf-regression smoke gate: bench smokes vs committed artifact bands.

ROADMAP item 5's regression-gate down-payment: the feed/fetch/upload
benches each have a ``--smoke`` tier-1 mode, but until now nothing
FAILED when a PR regressed the structural wins their committed artifacts
record (``FEED_r07.json``, ``FETCH_r08.json``, ``UPLOAD_r10.json``).
This tool runs the three smokes into a temp dir and checks each against
bands **derived from the committed artifact**, chosen to be robust to
this container's scheduler noise:

* structural invariants are exact — parity flags true, packed
  transfers-per-tile == 1, warm-store decode fully skipped
  (hit rate ≈ 100%);
* ratio invariants are banded — a smoke speedup / hit rate must reach a
  fraction of the committed value (a real regression to 1.0× fails; a
  noisy-but-working run passes).

Exit 0 = all bands met, 1 = regression (failed checks listed), 2 =
usage/IO error.  Wired into tier-1 via ``tests/test_upload.py``.

Usage:
    python tools/perf_gate.py            # smoke benches vs committed bands
    python tools/perf_gate.py --json     # machine-readable verdict only
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

#: committed artifacts of record — the baselines the bands derive from
FEED_BASELINE = REPO / "FEED_r07.json"
FETCH_BASELINE = REPO / "FETCH_r08.json"
UPLOAD_BASELINE = REPO / "UPLOAD_r10.json"
SERVE_BASELINE = REPO / "SERVE_r11.json"
FLIGHT_BASELINE = REPO / "FLIGHT_r12.json"

#: a smoke ratio must reach this fraction of its committed value — loose
#: enough for a 2-core container's noise, tight enough that a regression
#: to parity (1.0×) always fails
RATIO_BAND = 1 / 3
#: speedup floor even when the band would dip below it (a "speedup" of
#: 1.0 means the optimization is off, whatever the baseline said)
SPEEDUP_FLOOR = 1.15


def _hit_rate(stats: dict) -> float | None:
    lookups = stats.get("hits", 0) + stats.get("misses", 0)
    return stats.get("hits", 0) / lookups if lookups else None


def run_gate(workdir: str, checks: list) -> None:
    """Run the five bench smokes and append (name, ok, detail) rows."""
    import feed_bench
    import fetch_bench
    import flight_overhead
    import serve_bench
    import upload_bench

    def check(name: str, ok: bool, detail: str) -> None:
        checks.append({"check": name, "ok": bool(ok), "detail": detail})

    # -- feed (RAM decode cache) ------------------------------------------
    base = json.loads(FEED_BASELINE.read_text())
    out = str(Path(workdir) / "feed_smoke.json")
    if feed_bench.main(["--smoke", "--out", out]) != 0:
        check("feed.ran", False, "feed_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check("feed.parity", got.get("parity_ok") is True, "cached reads byte-identical")
        base_hr = _hit_rate(base["cache_stats"]) or 0.0
        got_hr = _hit_rate(got["cache_stats"]) or 0.0
        band = base_hr * 0.5
        check(
            "feed.hit_rate",
            got_hr >= band,
            f"smoke hit rate {got_hr:.3f} vs band {band:.3f} "
            f"(committed {base_hr:.3f})",
        )
        check(
            "feed.cache_hits",
            got["cache_stats"].get("hits", 0) > 0,
            "cache served at least one revisit",
        )

    # -- fetch (packed device→host) ---------------------------------------
    base = json.loads(FETCH_BASELINE.read_text())
    out = str(Path(workdir) / "fetch_smoke.json")
    if fetch_bench.main(["--smoke", "--out", out]) != 0:
        check("fetch.ran", False, "fetch_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check("fetch.parity", got["parity"]["ok"] is True, "packed ≡ per-product")
        check(
            "fetch.transfers_per_tile",
            got["workload"]["transfers_per_tile_packed"] == 1,
            "packed fetch is one transfer per tile",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_packed_sync"] * RATIO_BAND)
        sp = max(got["speedup_packed_sync"], got["speedup_packed_async"])
        check(
            "fetch.speedup",
            sp >= band,
            f"smoke speedup {sp:.2f} vs band {band:.2f} "
            f"(committed {base['speedup_packed_sync']:.2f})",
        )

    # -- upload (packed host→device) + ingest store -----------------------
    base = json.loads(UPLOAD_BASELINE.read_text())
    out = str(Path(workdir) / "upload_smoke.json")
    if upload_bench.main(["--smoke", "--out", out]) != 0:
        check("upload.ran", False, "upload_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check("upload.parity", got["parity"]["ok"] is True, "unpacked ≡ fed arrays")
        check(
            "upload.transfers_per_tile",
            got["workload"]["transfers_per_tile_packed"] == 1,
            "packed upload is one transfer per tile",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_packed_sync"] * RATIO_BAND)
        sp = max(got["speedup_packed_sync"], got["speedup_packed_async"])
        check(
            "upload.speedup",
            sp >= band,
            f"smoke speedup {sp:.2f} vs band {band:.2f} "
            f"(committed {base['speedup_packed_sync']:.2f})",
        )
        store = got.get("ingest_store")
        if store is None:
            check("store.ran", False, "smoke skipped the ingest-store phase")
        else:
            check(
                "store.parity", store["parity_ok"] is True,
                "store-served window reads byte-identical",
            )
            # structural, not a noisy wall ratio: the warm/restart passes
            # must skip TIFF decode entirely (the acceptance invariant)
            for leg in ("store_warm", "store_restart"):
                check(
                    f"store.{leg}_decode_skipped",
                    store[leg]["stats"]["misses"] == 0
                    and store[leg]["hit_rate"] is not None
                    and store[leg]["hit_rate"] >= 0.99,
                    f"{leg}: hit rate {store[leg]['hit_rate']} with "
                    f"{store[leg]['stats']['misses']} misses",
                )

    # -- serve (warm program cache + shared ingest store) -----------------
    base = json.loads(SERVE_BASELINE.read_text())
    out = str(Path(workdir) / "serve_smoke.json")
    if serve_bench.main(["--smoke", "--out", out]) != 0:
        check("serve.ran", False, "serve_bench --smoke exited nonzero")
    else:
        got = json.loads(Path(out).read_text())
        check(
            "serve.parity", got["parity_ok"] is True,
            "warm job artifacts ≡ cold job artifacts",
        )
        # THE structural acceptance invariant: a warm job submitted to a
        # running server performs zero jit compiles (program-cache hit)
        # and zero TIFF decodes (every block store-served) — exact, not
        # a noisy wall ratio
        inv = got["invariants"]
        check(
            "serve.warm_zero_compiles",
            inv["warm_zero_compiles"] is True,
            f"warm program_cache: {got['warm']['program_cache']}",
        )
        check(
            "serve.warm_zero_decodes",
            inv["warm_zero_decodes"] is True,
            f"warm ingest_store: {got['warm']['ingest_store']}",
        )
        band = max(SPEEDUP_FLOOR, base["speedup_warm"] * RATIO_BAND)
        check(
            "serve.warm_speedup",
            got["speedup_warm"] is not None
            and got["speedup_warm"] >= band,
            f"smoke warm speedup {got['speedup_warm']} vs band "
            f"{band:.2f} (committed {base['speedup_warm']})",
        )

    # -- flight recorder (ring + sampler overhead) ------------------------
    base = json.loads(FLIGHT_BASELINE.read_text())
    out = str(Path(workdir) / "flight_smoke.json")
    try:
        got = flight_overhead.run_bench(smoke=True, out_path=out)
    except Exception as e:
        check("flight.ran", False, f"flight_overhead smoke raised: {e}")
    else:
        # structural, exact: the on-run's ring dump is a schema-valid
        # events slice and the sampler series is non-empty
        fl = got.get("flight", {})
        check(
            "flight.dump_valid",
            fl.get("dump_valid") is True,
            f"flight.jsonl schema-valid ({fl.get('dump_errors')})",
        )
        check(
            "flight.sampler_fired",
            fl.get("samples", 0) >= 1,
            f"{fl.get('samples', 0)} flight_sample events in the dump",
        )
        # the documented noise band from the committed artifact, checked
        # on the MIN-of-reps overhead (container jitter only inflates
        # wall time; a real regression — a lock on the emit path, an
        # O(n) ring scan — inflates the cost floor itself)
        band = float(base["noise_band_pct"])
        check(
            "flight.overhead",
            got["overhead_min_pct"] <= band,
            f"smoke min-rep overhead {got['overhead_min_pct']}% (median "
            f"{got['overhead_pct']}%) vs documented noise band {band}% "
            f"(committed {base['overhead_min_pct']}%)",
        )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable verdict only")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the smoke artifacts under DIR")
    args = ap.parse_args(argv)

    for p in (FEED_BASELINE, FETCH_BASELINE, UPLOAD_BASELINE,
              SERVE_BASELINE, FLIGHT_BASELINE):
        if not p.exists():
            print(f"error: committed baseline {p.name} missing", file=sys.stderr)
            return 2

    workdir = args.keep or tempfile.mkdtemp(prefix="lt_perf_gate_")
    Path(workdir).mkdir(parents=True, exist_ok=True)
    checks: list = []
    try:
        run_gate(workdir, checks)
    finally:
        if args.keep is None:
            shutil.rmtree(workdir, ignore_errors=True)

    failed = [c for c in checks if not c["ok"]]
    verdict = {"ok": not failed, "checks": checks}
    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        for c in checks:
            print(f"  {'ok  ' if c['ok'] else 'FAIL'} {c['check']}: {c['detail']}")
        print(json.dumps({"ok": verdict["ok"], "checks": len(checks),
                          "failed": len(failed)}))
    if failed and not args.json:
        for c in failed:
            print(f"regression: {c['check']}: {c['detail']}", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
