"""Lint event-log files against the obs event schema (CI seam).

Validates one or more ``events.jsonl`` files (or workdirs containing them)
against :data:`land_trendr_tpu.obs.events.EVENT_FIELDS` at the current
:data:`~land_trendr_tpu.obs.events.SCHEMA_VERSION`: every line parses,
every event is a known type with its required fields at the right types,
and the stream opens with ``run_start``.  On top of the type schema, the
subsystem rollups get VALUE lints a type check alone cannot catch:
``feed_cache`` (the feed-path decode subsystem, ``io/blockcache``) must
have non-negative counters and readahead hits cannot exceed the blocks
readahead inserted; ``fetch`` (the device→host fetch subsystem,
``runtime/fetch``) must have non-negative counters, at least one transfer
per fetched tile, and an ``unpack_s`` that fits inside its scope's
``run_done`` write-stage seconds (unpack always runs inside the write
stage — a larger value means a broken stats split).  Exit 0 = all clean,
1 = schema errors (listed on stderr), 2 = usage/IO error.

This is the guard that keeps producer (driver) and consumers
(``obs_report``, dashboards) honest about the JSONL contract — wired into
the tier-1 test run as a fast test (``tests/test_obs.py``), and runnable
against any run's workdir:

    python tools/check_events_schema.py lt_work/
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.obs.events import (  # noqa: E402
    SCHEMA_VERSION,
    expand_event_paths,
    validate_events_file,
)

#: numeric fields that can never go negative, per event type (counters
#: and byte gauges alike — a negative value means a broken stats delta).
#: EXPORTED data, not a private tuple: the static emit-site rule
#: (``land_trendr_tpu/lintkit/eventschema.py`` LT005) imports this table
#: and cross-checks every name against the schema's
#: ``EVENT_FIELDS``/``OPTIONAL_FIELDS``, so the runtime value lint and
#: the static lint can never drift onto two parallel field lists.
NONNEG_FIELDS: dict[str, tuple[str, ...]] = {
    "feed_cache": (
        "hits", "misses", "evictions", "decode_s", "inserted_bytes",
        "readahead_blocks", "readahead_hits", "readahead_dropped",
        "cache_bytes", "budget_bytes", "corrupt_dropped",
    ),
    "fetch": (
        "tiles", "transfers", "bytes", "pack_s", "wait_s", "unpack_s",
        "backlog_max",
    ),
    "upload": (
        "tiles", "transfers", "bytes", "pack_s", "wait_s", "unpack_s",
        "backlog_max",
    ),
    "upload_demoted": ("failures",),
    "ingest_store": (
        "hits", "misses", "put_blocks", "put_bytes", "stale_dropped",
        "corrupt_dropped", "evicted_segments", "bytes", "budget_bytes",
        "segments",
    ),
    # tracing events (obs/spans): monotonic-clock values and durations
    # are non-negative by construction; a negative one means a broken
    # producer clock pairing
    "span": ("tile_id", "start", "end", "attempt"),
    "tile_straggler": (
        "tile_id", "duration_s", "threshold_s", "median_s", "attempt",
    ),
    # robustness events (PR 5): counters/indices/durations only go up
    "fault_injected": ("index",),
    "tile_quarantined": ("tile_id", "attempts"),
    "stall": ("idle_s", "timeout_s"),
    "fetch_demoted": ("failures",),
    "run_done": ("tiles_quarantined", "tiles_stolen", "tiles_speculated"),
    # elastic pod scheduling (runtime/leases): tile ids and lease
    # generations only count up
    "tile_leased": ("tile_id", "gen"),
    "lease_stolen": ("tile_id", "gen"),
    "tile_speculated": ("tile_id", "gen"),
    # serve-mode events (land_trendr_tpu/serve): queue depths, waits,
    # latencies and warm-cache counters only go up / never negative
    "job_submitted": ("queue_depth",),
    "job_start": ("wait_s",),
    "job_done": ("wall_s", "tiles_quarantined"),
    "job_rejected": ("queue_depth",),
    "program_cache": ("hits", "misses", "compile_s", "keys"),
    # flight recorder / live debug surface: resource samples, capture
    # verdicts and SLO accounting are gauges/durations — never negative
    "flight_sample": (
        "rss_bytes", "open_fds", "threads", "feed_backlog",
        "write_backlog", "fetch_backlog", "upload_backlog", "queue_depth",
        "running", "jobs_total", "warm_program_count", "cache_bytes",
        "store_bytes", "device_bytes_in_use", "stragglers",
        "tiles_stolen", "tiles_speculated",
    ),
    "profile_captured": ("duration_s", "bytes"),
    "job_slo": ("queue_wait_s", "exec_s", "latency_s", "deadline_s"),
    # fleet telemetry plane: alert lifecycle durations and pod-fold
    # host-health counts only go up / never negative (the state-enum and
    # firing-before-resolved checks live in AlertValueLint below)
    "alert": ("duration_s", "window_s"),
    "fleet_sample": (
        "hosts", "stale_hosts", "corrupt_snaps", "alerts_firing",
        "history_samples",
    ),
    # serving-fleet router (land_trendr_tpu/fleet): queue depths, route
    # attempts and pool sizes only count up / never negative (the
    # route_decision attempt >= 1 cross-check lives in
    # route_decision_value_errors below)
    "route_decision": ("attempt", "queue_wait_s", "queue_depth"),
    "replica_down": ("inflight",),
    "tenant_throttled": ("queue_depth",),
    "scale_decision": ("burn", "replicas", "queue_depth"),
    # autotuned execution profiles (land_trendr_tpu/tune): probe counts,
    # walls, speedups and profile ages only go up / never negative (the
    # source-enum and store-implies-zero-probes checks live in
    # tune_value_errors below)
    "tune_probe": ("probes", "wall_s", "speedup"),
    "tune_profile": ("probes", "age_s", "groups"),
    # end-to-end request tracing (obs/reqtrace): monotonic span bounds,
    # end-to-end latencies and hop counts only go up / never negative
    # (the blame-sum and orphan-trace checks live in
    # request_value_errors / TraceRefLint below)
    "request_span": ("start", "end", "attempt"),
    "request_done": ("latency_s", "hops"),
    # fleet-scale load harness + capacity planner (loadgen/,
    # fleet/capacity): rates, quantiles, counts and replay walls only
    # go up / never negative (the strict positivity, quantile-order,
    # blame-vocabulary and replay-implication checks live in
    # capacity_value_errors below)
    "load_phase": (
        "offered_qps", "requests", "workers", "duration_s", "seed",
    ),
    "sweep_point": (
        "replicas", "offered_qps", "achieved_qps", "p50_s", "p99_s",
        "goodput_qps", "done", "failed", "rejected", "window_s",
        "assembled",
    ),
    "sim_replay": (
        "decisions", "matched", "speedup_x", "recorded_span_s",
        "replay_wall_s", "mismatch_seq",
    ),
    # cross-job continuous batching (serve/batching): coalesced job/tile
    # counts, padded pixels, occupancy and window waits only go up /
    # never negative (the tiles >= jobs >= 1 and 0 < occupancy <= 1
    # cross-checks live in batch_value_errors below)
    "batch_launch": (
        "jobs", "tiles", "padded_px", "occupancy", "window_wait_s",
    ),
    "batch_demux": ("tiles", "member_jobs"),
    # crash-safe control plane (fleet/journal): segment indices, record
    # sizes and recovery counters only go up / never negative (the
    # record-kind enum, >= 1 floors and recovery-split cross-checks live
    # in journal_value_errors below)
    "journal_append": ("segment", "bytes"),
    "router_recovered": (
        "replayed", "relayed", "requeued", "deduped", "recovery_s",
        "reattached",
    ),
}


def feed_cache_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for one ``feed_cache`` record (type checks are the
    schema's job — :func:`validate_event` already covers those)."""
    if not isinstance(rec, dict) or rec.get("ev") != "feed_cache":
        return []
    errs = []
    for name in NONNEG_FIELDS["feed_cache"]:
        v = rec.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v < 0:
            errs.append(f"line {lineno}: feed_cache: {name} is negative ({v})")
    ra_b, ra_h = rec.get("readahead_blocks"), rec.get("readahead_hits")
    if (
        isinstance(ra_b, int) and isinstance(ra_h, int)
        and not isinstance(ra_b, bool) and not isinstance(ra_h, bool)
        and ra_h > ra_b
    ):
        errs.append(
            f"line {lineno}: feed_cache: readahead_hits {ra_h} exceeds "
            f"readahead_blocks {ra_b} (each readahead block is counted "
            "as a hit at most once)"
        )
    return errs


#: slack for the unpack_s ≤ write_s cross-check: both sides are rounded
#: independently (event fields to 6 dp, stage_s to 4 dp)
_UNPACK_SLACK_S = 1e-3


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class FetchValueLint:
    """Value lint for ``fetch`` records, one instance per file.

    Stateful across records because one invariant is cross-event: the
    fetch rollup's ``unpack_s`` accumulates inside the driver's write
    stage, so it must fit within the same run scope's ``run_done``
    ``stage_s.write_s`` (summed across writer threads, like unpack_s).
    ``run_start`` opens a new scope and resets the pending check.
    """

    def __init__(self) -> None:
        self._pending: "tuple[int, float] | None" = None  # (lineno, unpack_s)

    def __call__(self, rec, lineno: int) -> list[str]:
        if not isinstance(rec, dict):
            return []
        ev = rec.get("ev")
        if ev == "run_start":
            self._pending = None
            return []
        if ev == "run_done":
            errs = []
            stage_s = rec.get("stage_s")
            if self._pending is not None and isinstance(stage_s, dict):
                fx_line, unpack_s = self._pending
                write_s = stage_s.get("write_s")
                if _num(write_s) and unpack_s > write_s + _UNPACK_SLACK_S:
                    errs.append(
                        f"line {fx_line}: fetch: unpack_s {unpack_s} exceeds "
                        f"the scope's write-stage seconds {write_s} "
                        f"(run_done line {lineno}; unpack runs inside the "
                        "write stage)"
                    )
            self._pending = None
            return errs
        if ev != "fetch":
            return []
        errs = []
        for name in NONNEG_FIELDS["fetch"]:
            v = rec.get(name)
            if _num(v) and v < 0:
                errs.append(f"line {lineno}: fetch: {name} is negative ({v})")
        tiles, transfers = rec.get("tiles"), rec.get("transfers")
        if _num(tiles) and _num(transfers) and transfers < tiles:
            errs.append(
                f"line {lineno}: fetch: transfers {transfers} below tiles "
                f"{tiles} (every fetched tile costs at least one transfer)"
            )
        if _num(rec.get("unpack_s")):
            self._pending = (lineno, rec["unpack_s"])
        return errs


def upload_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for one ``upload`` record: non-negative counters
    and at least one transfer per uploaded tile (the packed path's whole
    claim is transfers == tiles; the per-array path's is bands+1 per
    tile — below tiles means a broken stats split either way)."""
    if not isinstance(rec, dict) or rec.get("ev") != "upload":
        return []
    errs = []
    for name in NONNEG_FIELDS["upload"]:
        v = rec.get(name)
        if _num(v) and v < 0:
            errs.append(f"line {lineno}: upload: {name} is negative ({v})")
    tiles, transfers = rec.get("tiles"), rec.get("transfers")
    if _num(tiles) and _num(transfers) and transfers < tiles:
        errs.append(
            f"line {lineno}: upload: transfers {transfers} below tiles "
            f"{tiles} (every uploaded tile costs at least one transfer)"
        )
    return errs


#: slack for the SLO split cross-check: queue_wait_s + exec_s and
#: latency_s are rounded independently to 6 dp at the producer
_SLO_SPLIT_SLACK_S = 5e-3


def job_slo_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for one ``job_slo`` record: the split must ADD
    UP — ``queue_wait_s + exec_s`` cannot exceed ``latency_s`` beyond
    rounding slack (the three come from the same two timestamps; a
    larger gap means a broken split).  Non-negativity rides the generic
    loop — only the cross-field check lives here."""
    if not isinstance(rec, dict) or rec.get("ev") != "job_slo":
        return []
    errs = []
    qw, ex, lat = (
        rec.get("queue_wait_s"), rec.get("exec_s"), rec.get("latency_s")
    )
    if (
        _num(qw) and _num(ex) and _num(lat)
        and qw + ex > lat + _SLO_SPLIT_SLACK_S
    ):
        errs.append(
            f"line {lineno}: job_slo: queue_wait_s {qw} + exec_s {ex} "
            f"exceeds latency_s {lat} (the split must fit inside the "
            "end-to-end latency)"
        )
    return errs


#: slack for the span end >= start cross-check: both ends are rounded
#: to 6 dp at the producer (rounding is monotone, so a producer-true
#: ordering survives; the slack only forgives foreign re-rounding)
_SPAN_SLACK_S = 1e-6


def span_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for one ``span`` record: the span must close
    after it opened (``end >= start`` — both are the same monotonic
    clock, so a violation means a broken producer pairing, not skew)."""
    if not isinstance(rec, dict) or rec.get("ev") != "span":
        return []
    s, e = rec.get("start"), rec.get("end")
    if _num(s) and _num(e) and e < s - _SPAN_SLACK_S:
        return [
            f"line {lineno}: span: end {e} precedes start {s} "
            "(a span closes after it opens)"
        ]
    return []


def tile_straggler_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for one ``tile_straggler`` record: a straggler
    is BY DEFINITION over its threshold (``duration_s >= threshold_s``)
    and the threshold derives from the median (``threshold_s >=
    median_s`` — k >= 1 is enforced at the detector).  Non-negativity
    rides the generic loop."""
    if not isinstance(rec, dict) or rec.get("ev") != "tile_straggler":
        return []
    errs = []
    dur, thr, med = (
        rec.get("duration_s"), rec.get("threshold_s"), rec.get("median_s")
    )
    if _num(dur) and _num(thr) and dur < thr:
        errs.append(
            f"line {lineno}: tile_straggler: duration_s {dur} below "
            f"threshold_s {thr} (a straggler is over its threshold by "
            "definition)"
        )
    if _num(thr) and _num(med) and thr < med:
        errs.append(
            f"line {lineno}: tile_straggler: threshold_s {thr} below "
            f"median_s {med} (threshold = k x median with k >= 1)"
        )
    return errs


def lease_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for the elastic-scheduling acquisition events: a
    steal or a speculative re-lease is BY CONSTRUCTION a successor
    generation (the tile had a lease to steal from / speculate against),
    so ``gen >= 1`` — a 0 means the producer claimed a never-leased tile
    under the wrong event type.  Non-negativity rides the generic loop."""
    if not isinstance(rec, dict) or rec.get("ev") not in (
        "lease_stolen", "tile_speculated"
    ):
        return []
    gen = rec.get("gen")
    if _num(gen) and gen < 1:
        return [
            f"line {lineno}: {rec['ev']}: gen {gen} below 1 (a steal/"
            "speculation always claims a successor generation)"
        ]
    return []


#: the router's replica_down reason vocabulary (mirrors
#: land_trendr_tpu.fleet.router.DOWN_REASONS — asserted equal in
#: tests/test_fleet_serve.py so the two cannot drift)
DOWN_REASONS = ("health", "dead", "scale_down", "shutdown")

#: the autoscaler's direction vocabulary
SCALE_DIRECTIONS = ("up", "down")


def route_decision_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for the router events a type check alone cannot
    pin: a ``route_decision`` is BY DEFINITION at least the first
    attempt (``attempt >= 1``), a ``replica_down`` carries a known
    reason, and a ``scale_decision`` a known direction.  Non-negativity
    rides the generic loop."""
    if not isinstance(rec, dict):
        return []
    ev = rec.get("ev")
    if ev == "route_decision":
        att = rec.get("attempt")
        if _num(att) and att < 1:
            return [
                f"line {lineno}: route_decision: attempt {att} below 1 "
                "(a forward is at least the first attempt)"
            ]
        return []
    if ev == "replica_down":
        reason = rec.get("reason")
        if isinstance(reason, str) and reason not in DOWN_REASONS:
            return [
                f"line {lineno}: replica_down: reason {reason!r} not one "
                f"of {DOWN_REASONS}"
            ]
        return []
    if ev == "scale_decision":
        d = rec.get("direction")
        if isinstance(d, str) and d not in SCALE_DIRECTIONS:
            return [
                f"line {lineno}: scale_decision: direction {d!r} not one "
                f"of {SCALE_DIRECTIONS}"
            ]
        return []
    return []


#: the tune_profile source vocabulary (mirrors the autotuner's emit
#: sites in land_trendr_tpu/tune/autotune.py and the driver's resolution
#: — asserted non-drifting in tests/test_tune.py)
TUNE_SOURCES = ("probed", "store", "defaults")


def tune_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for the autotuner events a type check alone
    cannot pin: a ``tune_profile`` carries a known ``source``, and
    ``source="store"`` is BY DEFINITION a zero-probe reload; a
    ``tune_probe`` that succeeded (``ok=true``) ran at least one timed
    rep.  Non-negativity rides the generic loop."""
    if not isinstance(rec, dict):
        return []
    ev = rec.get("ev")
    if ev == "tune_profile":
        errs = []
        source = rec.get("source")
        if isinstance(source, str) and source not in TUNE_SOURCES:
            errs.append(
                f"line {lineno}: tune_profile: source {source!r} not one "
                f"of {TUNE_SOURCES}"
            )
        probes = rec.get("probes")
        if source == "store" and _num(probes) and probes != 0:
            errs.append(
                f"line {lineno}: tune_profile: source 'store' with "
                f"probes {probes} (a store reload runs zero probes by "
                "definition)"
            )
        return errs
    if ev == "tune_probe":
        probes = rec.get("probes")
        if rec.get("ok") is True and _num(probes) and probes < 1:
            return [
                f"line {lineno}: tune_probe: ok=true with probes "
                f"{probes} (a succeeded group ran at least one timed rep)"
            ]
        return []
    return []


#: slack for the request_done blame-sum cross-check: the blame is a
#: partition whose components were each rounded to 6 dp (≤ 5 of them),
#: and latency_s is rounded independently
_BLAME_SLACK_S = 5e-3


def request_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for the request-tracing events: a
    ``request_span`` closes after it opens (same monotonic clock — the
    ``span`` rule), and a ``request_done``'s blame components are a
    PARTITION of its latency, so they must sum to ``latency_s`` within
    rounding slack (the router computes the replica share as the exact
    residual — a larger gap means a broken split).  Non-negativity
    rides the generic loop."""
    if not isinstance(rec, dict):
        return []
    ev = rec.get("ev")
    if ev == "request_span":
        s, e = rec.get("start"), rec.get("end")
        if _num(s) and _num(e) and e < s - _SPAN_SLACK_S:
            return [
                f"line {lineno}: request_span: end {e} precedes start "
                f"{s} (a span closes after it opens)"
            ]
        return []
    if ev == "request_done":
        errs = []
        hops = rec.get("hops")
        blame, lat = rec.get("blame"), rec.get("latency_s")
        if isinstance(blame, dict) and _num(lat):
            vals = list(blame.values())
            if all(_num(v) for v in vals):
                for k, v in blame.items():
                    if v < 0:
                        errs.append(
                            f"line {lineno}: request_done: blame "
                            f"component {k!r} is negative ({v})"
                        )
                total = sum(vals)
                if abs(total - lat) > _BLAME_SLACK_S:
                    errs.append(
                        f"line {lineno}: request_done: blame components "
                        f"sum to {total} but latency_s is {lat} (the "
                        "blame is a partition of the latency)"
                    )
        if _num(hops) and hops >= 1 and isinstance(blame, dict) \
                and "forward" not in blame:
            # a routed request spent time forwarding by definition
            # (zero-hop requests — cancelled while queued — are the
            # only blame splits without a forward component)
            errs.append(
                f"line {lineno}: request_done: hops {hops} with no "
                "'forward' blame component"
            )
        return errs
    return []


#: the load-rig arrival-process vocabulary (mirrors
#: land_trendr_tpu.loadgen.config.LOAD_MODES — asserted equal in
#: tests/test_capacity.py so the two cannot drift)
LOAD_MODES = ("open", "closed")

#: the knee-blame vocabulary: the PR-15 blame priority
#: (land_trendr_tpu.obs.reqtrace.BLAME_PRIORITY) + the assembler's
#: "other" bucket for uncovered time — asserted equal in
#: tests/test_capacity.py so the two cannot drift
KNEE_BLAME_COMPONENTS = (
    "forward", "relay", "throttle_backoff", "route_queue",
    "replica_queue", "compile", "compute", "fetch", "upload", "feed",
    "write", "other",
)


def capacity_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for the capacity-planner events: an offered
    rate is strictly positive when present (a zero-rate phase/sweep
    point measures nothing), a sweep point's quantiles are ordered
    (p99 >= p50 by definition), its ``knee_blame`` names a component of
    the PR-15 blame vocabulary, and a ``sim_replay`` that claims
    ``match`` reproduced every recorded decision.  Non-negativity rides
    the generic loop."""
    if not isinstance(rec, dict):
        return []
    ev = rec.get("ev")
    if ev == "load_phase":
        errs = []
        mode = rec.get("mode")
        if isinstance(mode, str) and mode not in LOAD_MODES:
            errs.append(
                f"line {lineno}: load_phase: mode {mode!r} not one of "
                f"{LOAD_MODES}"
            )
        qps = rec.get("offered_qps")
        if _num(qps) and qps <= 0:
            errs.append(
                f"line {lineno}: load_phase: offered_qps {qps} not "
                "strictly positive (a zero-rate phase measures nothing)"
            )
        return errs
    if ev == "sweep_point":
        errs = []
        qps = rec.get("offered_qps")
        if _num(qps) and qps <= 0:
            errs.append(
                f"line {lineno}: sweep_point: offered_qps {qps} not "
                "strictly positive (a zero-rate sweep point measures "
                "nothing)"
            )
        p50, p99 = rec.get("p50_s"), rec.get("p99_s")
        if _num(p50) and _num(p99) and p99 < p50:
            errs.append(
                f"line {lineno}: sweep_point: p99_s {p99} below p50_s "
                f"{p50} (quantiles are ordered by definition)"
            )
        blame = rec.get("knee_blame")
        if isinstance(blame, str) and blame not in KNEE_BLAME_COMPONENTS:
            errs.append(
                f"line {lineno}: sweep_point: knee_blame {blame!r} not "
                f"in the blame vocabulary {KNEE_BLAME_COMPONENTS}"
            )
        return errs
    if ev == "sim_replay":
        errs = []
        dec, matched = rec.get("decisions"), rec.get("matched")
        if _num(dec) and _num(matched) and matched > dec:
            errs.append(
                f"line {lineno}: sim_replay: matched {matched} exceeds "
                f"decisions {dec}"
            )
        if rec.get("match") is True and _num(dec) and _num(matched) \
                and matched != dec:
            errs.append(
                f"line {lineno}: sim_replay: match=true with matched "
                f"{matched} != decisions {dec} (match means every "
                "recorded decision was reproduced)"
            )
        return errs
    return []


class TraceRefLint:
    """Referential-integrity lint for ``trace_id``, one instance per
    file.

    Stateful because the invariant is cross-event: every
    ``trace_id``-stamped span in a stream must resolve to the event
    that INTRODUCED that id — a ``job_submitted`` or ``route_decision``
    carrying it (router and serve scopes), or the scope's own
    ``run_start`` (a job run scope stamps the id as a common field, so
    its ``run_start`` is the introduction).  An orphan span means a
    producer stamped an id the stream never admitted — a broken
    propagation chain.  ``run_start`` opens a new scope and resets the
    known set (seeding it with its own stamp).
    """

    #: events that introduce a trace id into the scope
    _INTRODUCERS = ("job_submitted", "route_decision")
    #: span-like events whose trace_id must resolve
    _CHECKED = ("request_span", "request_done", "span")

    def __init__(self) -> None:
        self._known: set = set()

    def __call__(self, rec, lineno: int) -> list[str]:
        if not isinstance(rec, dict):
            return []
        ev = rec.get("ev")
        tid = rec.get("trace_id")
        if ev == "run_start":
            self._known.clear()
            if isinstance(tid, str):
                self._known.add(tid)
            return []
        if ev in self._INTRODUCERS and isinstance(tid, str):
            self._known.add(tid)
            return []
        if ev in self._CHECKED and isinstance(tid, str) \
                and tid not in self._known:
            return [
                f"line {lineno}: {ev}: trace_id {tid!r} was never "
                "introduced in this scope (no job_submitted / "
                "route_decision / run_start carries it — orphan trace)"
            ]
        return []


#: the alert event's state vocabulary (mirrors
#: land_trendr_tpu.obs.alerts.ALERT_STATES — asserted equal in
#: tests/test_fleet.py so the two cannot drift)
ALERT_STATES = ("firing", "resolved")


class AlertValueLint:
    """Value lint for ``alert`` records, one instance per file.

    Stateful because the lifecycle is cross-event: a ``resolved``
    transition for a rule must follow a ``firing`` one in the same run
    scope (the engine can only resolve what fired), and two ``firing``
    transitions without a resolve between them mean a broken state
    machine.  ``run_start`` opens a new scope and resets every rule.
    """

    def __init__(self) -> None:
        self._firing: set = set()

    def __call__(self, rec, lineno: int) -> list[str]:
        if not isinstance(rec, dict):
            return []
        ev = rec.get("ev")
        if ev == "run_start":
            self._firing.clear()
            return []
        if ev != "alert":
            return []
        errs = []
        state, rule = rec.get("state"), rec.get("rule")
        if isinstance(state, str) and state not in ALERT_STATES:
            errs.append(
                f"line {lineno}: alert: state {state!r} not one of "
                f"{ALERT_STATES}"
            )
        if isinstance(rule, str) and state in ALERT_STATES:
            if state == "firing":
                if rule in self._firing:
                    errs.append(
                        f"line {lineno}: alert: rule {rule!r} fired twice "
                        "without resolving (broken lifecycle)"
                    )
                self._firing.add(rule)
            else:  # resolved
                if rule not in self._firing:
                    errs.append(
                        f"line {lineno}: alert: rule {rule!r} resolved "
                        "without a prior firing in this scope"
                    )
                self._firing.discard(rule)
        return errs


def batch_value_errors(rec, lineno: int) -> list[str]:
    """Value lint for ``batch_launch`` records: a shared launch
    coalesces at least its leader (``jobs >= 1``), every member brings
    at least one tile (``tiles >= jobs``), and occupancy is a fraction
    of the padded batch (``0 < occupancy <= 1`` — zero useful pixels
    means no launch to account for).  Non-negativity rides the generic
    NONNEG_FIELDS loop."""
    if not isinstance(rec, dict) or rec.get("ev") != "batch_launch":
        return []
    errs = []
    jobs, tiles = rec.get("jobs"), rec.get("tiles")
    if isinstance(jobs, int) and not isinstance(jobs, bool) and jobs < 1:
        errs.append(
            f"line {lineno}: batch_launch: jobs {jobs} < 1 (a launch "
            "coalesces at least its leader)"
        )
    if (
        isinstance(jobs, int) and isinstance(tiles, int)
        and not isinstance(jobs, bool) and not isinstance(tiles, bool)
        and tiles < jobs
    ):
        errs.append(
            f"line {lineno}: batch_launch: tiles {tiles} < jobs {jobs} "
            "(every coalesced job brings at least one tile)"
        )
    occ = rec.get("occupancy")
    if (
        isinstance(occ, (int, float)) and not isinstance(occ, bool)
        and not (0 < occ <= 1)
    ):
        errs.append(
            f"line {lineno}: batch_launch: occupancy {occ} outside "
            "(0, 1] (useful px over padded px)"
        )
    return errs


def journal_value_errors(rec, lineno: int) -> list[str]:
    """Value lint for the crash-safe control plane: a ``journal_append``
    names a known record kind and landed somewhere real (``segment`` and
    ``bytes`` both >= 1 — a zero-byte commit is a broken append path),
    and a ``router_recovered`` reconciliation split can only partition
    what was replayed (``relayed + requeued [+ reattached] <=
    replayed``).  Non-negativity rides the generic NONNEG_FIELDS loop."""
    if not isinstance(rec, dict):
        return []
    ev = rec.get("ev")
    errs = []
    if ev == "journal_append":
        kind = rec.get("rec")
        if isinstance(kind, str) and kind not in (
            "admitted", "forwarded", "terminal"
        ):
            errs.append(
                f"line {lineno}: journal_append: rec {kind!r} is not a "
                "journal record kind (admitted/forwarded/terminal)"
            )
        for name in ("segment", "bytes"):
            v = rec.get(name)
            if isinstance(v, int) and not isinstance(v, bool) and v < 1:
                errs.append(
                    f"line {lineno}: journal_append: {name} {v} < 1 "
                    "(a committed record has a segment and a size)"
                )
    elif ev == "router_recovered":
        parts = [rec.get(k) for k in ("relayed", "requeued", "reattached")]
        replayed = rec.get("replayed")
        ok = [
            v for v in parts
            if isinstance(v, int) and not isinstance(v, bool)
        ]
        if (
            isinstance(replayed, int) and not isinstance(replayed, bool)
            and sum(ok) > replayed
        ):
            errs.append(
                f"line {lineno}: router_recovered: reconciliation split "
                f"{sum(ok)} exceeds replayed {replayed} (relayed + "
                "requeued + reattached partition the replayed jobs)"
            )
    return errs


def generic_nonneg_errors(rec, lineno: int) -> list[str]:
    """Non-negativity for the event types without a dedicated lint class
    (the robustness events, the ingest-store rollup, the flight-sampler
    gauges, run_done's quarantine count) — one loop over the same
    exported table the dedicated lints share."""
    if not isinstance(rec, dict):
        return []
    ev = rec.get("ev")
    if ev not in NONNEG_FIELDS or ev in ("feed_cache", "fetch", "upload"):
        return []
    errs = []
    for name in NONNEG_FIELDS[ev]:
        v = rec.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v < 0:
            errs.append(f"line {lineno}: {ev}: {name} is negative ({v})")
    return errs


def value_lints():
    """Fresh per-file ``extra`` hook chaining every value-level lint."""
    fetch_lint = FetchValueLint()
    alert_lint = AlertValueLint()
    trace_lint = TraceRefLint()

    def extra(rec, lineno: int) -> list[str]:
        return (
            feed_cache_value_errors(rec, lineno)
            + fetch_lint(rec, lineno)
            + upload_value_errors(rec, lineno)
            + job_slo_value_errors(rec, lineno)
            + span_value_errors(rec, lineno)
            + tile_straggler_value_errors(rec, lineno)
            + lease_value_errors(rec, lineno)
            + route_decision_value_errors(rec, lineno)
            + tune_value_errors(rec, lineno)
            + request_value_errors(rec, lineno)
            + capacity_value_errors(rec, lineno)
            + batch_value_errors(rec, lineno)
            + journal_value_errors(rec, lineno)
            + alert_lint(rec, lineno)
            + trace_lint(rec, lineno)
            + generic_nonneg_errors(rec, lineno)
        )

    return extra


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="events.jsonl files, or workdirs containing them")
    ap.add_argument("--max-errors", type=int, default=20,
                    help="cap per-file error listing (all are counted)")
    args = ap.parse_args(argv)

    try:
        # the shared expansion contract (land_trendr_tpu.obs): pod
        # per-process files win over a stale events.jsonl, identically
        # for this lint and for obs_report
        files = expand_event_paths(args.paths)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    n_bad = 0
    for path in files:
        # one parse per file: the value-level feed_cache + fetch lints
        # ride the schema pass as a per-record hook, errors in line order
        errs = validate_events_file(path, extra=value_lints())
        if errs:
            n_bad += 1
            for e in errs[: args.max_errors]:
                print(f"{path}: {e}", file=sys.stderr)
            if len(errs) > args.max_errors:
                print(
                    f"{path}: ... and {len(errs) - args.max_errors} more",
                    file=sys.stderr,
                )
        else:
            print(f"{path}: OK (schema v{SCHEMA_VERSION})")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
