"""Lint event-log files against the obs event schema (CI seam).

Validates one or more ``events.jsonl`` files (or workdirs containing them)
against :data:`land_trendr_tpu.obs.events.EVENT_FIELDS` at the current
:data:`~land_trendr_tpu.obs.events.SCHEMA_VERSION`: every line parses,
every event is a known type with its required fields at the right types,
and the stream opens with ``run_start``.  On top of the type schema, the
``feed_cache`` rollup (the feed-path decode subsystem, ``io/blockcache``)
gets a VALUE lint: its counters must be non-negative and readahead hits
cannot exceed the blocks readahead inserted — producer drift a type check
alone cannot catch.  Exit 0 = all clean, 1 = schema errors (listed on
stderr), 2 = usage/IO error.

This is the guard that keeps producer (driver) and consumers
(``obs_report``, dashboards) honest about the JSONL contract — wired into
the tier-1 test run as a fast test (``tests/test_obs.py``), and runnable
against any run's workdir:

    python tools/check_events_schema.py lt_work/
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from land_trendr_tpu.obs.events import (  # noqa: E402
    SCHEMA_VERSION,
    expand_event_paths,
    validate_events_file,
)

#: numeric feed_cache fields that can never go negative (counters and
#: byte gauges alike — a negative value means a broken stats delta)
_FEED_CACHE_NONNEG = (
    "hits", "misses", "evictions", "decode_s", "inserted_bytes",
    "readahead_blocks", "readahead_hits", "readahead_dropped",
    "cache_bytes", "budget_bytes",
)


def feed_cache_value_errors(rec, lineno: int) -> list[str]:
    """Value-level lint for one ``feed_cache`` record (type checks are the
    schema's job — :func:`validate_event` already covers those)."""
    if not isinstance(rec, dict) or rec.get("ev") != "feed_cache":
        return []
    errs = []
    for name in _FEED_CACHE_NONNEG:
        v = rec.get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v < 0:
            errs.append(f"line {lineno}: feed_cache: {name} is negative ({v})")
    ra_b, ra_h = rec.get("readahead_blocks"), rec.get("readahead_hits")
    if (
        isinstance(ra_b, int) and isinstance(ra_h, int)
        and not isinstance(ra_b, bool) and not isinstance(ra_h, bool)
        and ra_h > ra_b
    ):
        errs.append(
            f"line {lineno}: feed_cache: readahead_hits {ra_h} exceeds "
            f"readahead_blocks {ra_b} (each readahead block is counted "
            "as a hit at most once)"
        )
    return errs


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="events.jsonl files, or workdirs containing them")
    ap.add_argument("--max-errors", type=int, default=20,
                    help="cap per-file error listing (all are counted)")
    args = ap.parse_args(argv)

    try:
        # the shared expansion contract (land_trendr_tpu.obs): pod
        # per-process files win over a stale events.jsonl, identically
        # for this lint and for obs_report
        files = expand_event_paths(args.paths)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    n_bad = 0
    for path in files:
        # one parse per file: the value-level feed_cache lint rides the
        # schema pass as a per-record hook, errors in line order
        errs = validate_events_file(path, extra=feed_cache_value_errors)
        if errs:
            n_bad += 1
            for e in errs[: args.max_errors]:
                print(f"{path}: {e}", file=sys.stderr)
            if len(errs) > args.max_errors:
                print(
                    f"{path}: ... and {len(errs) - args.max_errors} more",
                    file=sys.stderr,
                )
        else:
            print(f"{path}: OK (schema v{SCHEMA_VERSION})")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
