"""Request-tracing bench: a fleet replay with a mid-trace replica kill.

The acceptance driver for the request-tracing plane (ISSUE 15): replay
a short multi-tenant job trace through a real
:class:`~land_trendr_tpu.fleet.router.FleetRouter` over real spawned
``lt serve`` replica processes, SIGKILL the replica holding in-flight
work once its job has durable tiles, and prove — from the streams
alone — that

* the killed job reconstructs as **one trace with two forward hops**
  (the killed replica's and the survivor's) under a single
  ``trace_id``, with the re-route visible in its blame split;
* every terminal request's **blame components sum to the
  router-observed latency** (the partition property, checked per
  request against the ``request_done`` record AND the full cross-layer
  assembly);
* the **p99 exemplar** closes the metrics→traces loop: the tail bucket
  of ``lt_router_job_seconds`` (via ``/metrics/exemplars``) names a
  ``trace_id`` that assembles to a complete cross-layer trace;
* artifacts stay **byte-identical** across the kill (trace stamping is
  pure observation — the fault_soak/fleet_bench contract).

Writes the ``REQTRACE_*.json`` artifact of record.  Minutes-scale (two
cold jax replica processes), like ``fleet_bench``:

    python tools/reqtrace_bench.py --out REQTRACE_r16.json
"""

from __future__ import annotations

import argparse
import json
import shutil
import signal
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from fleet_bench import _digest_workdir, _percentile  # noqa: E402

from land_trendr_tpu.obs.reqtrace import (  # noqa: E402
    assemble_request,
    discover_request_files,
)

#: the fixed replay: (tenant, big scene?) in submission order — enough
#: volume for a latency distribution, one heavy-tail job
_TRACE = [
    ("agency", False), ("agency", False), ("alerts", False),
    ("agency", True), ("research", False), ("agency", False),
    ("alerts", False), ("agency", False),
]


def run_bench(root: Path, size: int, years: int, tile: int) -> dict:
    from land_trendr_tpu.fleet import FleetRouter, RouterConfig
    from land_trendr_tpu.io.synthetic import (
        SceneSpec,
        make_stack,
        write_stack,
    )

    scenes = {}
    for name, edge in (("small", size), ("big", size * 2)):
        d = str(root / f"stack_{name}")
        write_stack(d, make_stack(SceneSpec(
            width=edge, height=edge, year_start=2000,
            year_end=2000 + years - 1, seed=13,
        )))
        scenes[name] = d

    rt_dir = str(root / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir,
        spawn_replicas=2,
        health_interval_s=0.3,
        route_retries=3,
        # pace dispatches so the kill lands mid-job with durable tiles
        replica_args=(
            "--feed-cache-mb", "64",
            "--fault-schedule", "seed=5,dispatch%1.0=slow:0.3",
        ),
    ))
    thread = threading.Thread(target=router.serve_forever)
    thread.start()
    killed_rid = killed_trace = None
    submits: list = []
    try:
        deadline = time.monotonic() + 900
        for idx, (tenant, big) in enumerate(_TRACE):
            snap = router.submit({
                "stack_dir": scenes["big" if big else "small"],
                "tile_size": tile,
                "tenant": tenant,
                "params": {"max_segments": 4,
                           "vertex_count_overshoot": 2},
                "run_overrides": {"retry_backoff_s": 0.0},
            })
            submits.append(snap)
            if idx == len(_TRACE) // 3 and killed_rid is None:
                # SIGKILL the replica holding in-flight work, but only
                # once a held job has durable tiles (the resume proof)
                victim = vjob = None
                while time.monotonic() < deadline and victim is None:
                    with router._lock:
                        for r in router.pool:
                            if not (r.inflight and r.proc is not None
                                    and r.proc.poll() is None):
                                continue
                            for jid in sorted(r.inflight):
                                j = router._jobs.get(jid)
                                if j is not None and list(
                                    Path(j.workdir).glob("tile_*.npz")
                                ):
                                    victim, vjob = r, j
                                    break
                            if victim is not None:
                                break
                    if victim is None:
                        time.sleep(0.05)
                if victim is None:
                    raise RuntimeError(
                        "kill: no replica ever held a durable job"
                    )
                killed_rid, killed_trace = victim.rid, vjob.trace_id
                victim.proc.send_signal(signal.SIGKILL)
        # await every job terminal
        pending = {s["job_id"] for s in submits}
        results: dict = {}
        while pending and time.monotonic() < deadline:
            for jid in sorted(pending):
                s = router.job_status(jid)
                if s and s["state"] not in ("queued", "routed"):
                    results[jid] = s
            pending -= set(results)
            if pending:
                time.sleep(0.1)
        if pending:
            raise TimeoutError(f"jobs never finished: {pending}")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/metrics/exemplars",
            timeout=10,
        ) as r:
            exemplars = json.loads(r.read())["exemplars"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/debug/requests", timeout=10
        ) as r:
            recent = json.loads(r.read())["requests"]
    finally:
        router.stop()
        thread.join(timeout=600)

    # -- fold --------------------------------------------------------------
    streams = discover_request_files(rt_dir)
    states = [results[s["job_id"]] for s in submits]
    lost = [s for s in states if s["state"] != "done"]
    latencies = [r["latency_s"] for r in recent]

    killed_job = next(
        s for s in states if s["trace_id"] == killed_trace
    )
    killed_rec = assemble_request(streams, killed_trace)
    hop_replicas = [h["replica"] for h in killed_rec["hops"]]

    # per-request blame-sum check over EVERY terminal request: both the
    # router's request_done split and the full cross-layer partition
    blame_sums_ok = all(
        abs(sum(r["blame"].values()) - r["latency_s"]) <= 5e-3
        for r in recent
    )
    assembled = {
        s["trace_id"]: assemble_request(streams, s["trace_id"])
        for s in states
    }
    assembly_sums_ok = all(
        abs(rec["blame_sum_s"] - rec["latency_s"]) <= 5e-3
        for rec in assembled.values()
    )
    complete_ok = all(rec["complete"] for rec in assembled.values())

    # the p99 exemplar: the highest occupied bucket of the router's
    # job-latency histogram names a trace that must assemble complete
    job_ex = next(
        (e["exemplars"] for e in exemplars
         if e["name"] == "lt_router_job_seconds"), {},
    )
    def _le(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)
    tail_le = max(job_ex, key=_le, default=None)
    p99_trace = job_ex[tail_le][-1]["trace_id"] if tail_le else None
    p99_rec = assembled.get(p99_trace) or (
        assemble_request(streams, p99_trace) if p99_trace else {}
    )

    # artifact parity across the kill: the same spec's tiles are
    # byte-identical wherever (and however many times) they ran
    parity_ok = True
    ref: dict = {}
    for s in states:
        spec = s["key"]
        d = _digest_workdir(s["workdir"])
        if not d:
            parity_ok = False
        elif spec not in ref:
            ref[spec] = d
        elif ref[spec] != d:
            parity_ok = False

    invariants = {
        "zero_lost_jobs": not lost,
        "killed_job_two_hops": (
            len(killed_rec["hops"]) >= 2
            and hop_replicas[0] == killed_rid
            and hop_replicas[-1] != killed_rid
        ),
        "killed_job_one_trace": (
            killed_job["attempts"] >= 2
            and killed_rec["complete"] is True
        ),
        "blame_sums_to_latency": bool(
            blame_sums_ok and assembly_sums_ok
        ),
        "all_traces_assemble_complete": complete_ok,
        "p99_exemplar_assembles": (
            p99_rec.get("complete") is True
        ),
        "artifact_parity_across_kill": bool(parity_ok and ref),
    }
    return {
        "workload": {
            "jobs": len(_TRACE),
            "tenants": sorted({t for t, _ in _TRACE}),
            "scene_small_px": size * size,
            "scene_big_px": (size * 2) ** 2,
            "years": years,
            "tile_size": tile,
            "replicas": 2,
        },
        "killed_replica": killed_rid,
        "killed_trace": {
            "trace_id": killed_trace,
            "status": killed_job["state"],
            "route_attempts": killed_job["attempts"],
            "hops": killed_rec["hops"],
            "latency_s": killed_rec["latency_s"],
            "blame": killed_rec["blame"],
            "blame_sum_s": killed_rec["blame_sum_s"],
            "tiles_done": killed_rec["tiles_done"],
        },
        "p99_exemplar": {
            "bucket_le": tail_le,
            "trace_id": p99_trace,
            "complete": p99_rec.get("complete"),
            "latency_s": p99_rec.get("latency_s"),
            "blame": p99_rec.get("blame"),
        },
        "latency": {
            "p50_s": _percentile(latencies, 0.50),
            "p99_s": _percentile(latencies, 0.99),
        },
        "requests_folded": len(recent),
        "streams": len(streams),
        "invariants": invariants,
        "ok": all(invariants.values()),
    }


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--size", type=int, default=40,
                    help="small-scene edge px (big is 2x)")
    ap.add_argument("--years", type=int, default=7)
    ap.add_argument("--tile", type=int, default=20)
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="keep the bench workdirs under DIR")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON artifact here")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", jax.config.jax_platforms or "cpu")

    root = Path(args.keep or tempfile.mkdtemp(prefix="lt_reqtrace_"))
    root.mkdir(parents=True, exist_ok=True)
    try:
        report = run_bench(root, args.size, args.years, args.tile)
    finally:
        if args.keep is None:
            shutil.rmtree(root, ignore_errors=True)

    if args.out:
        from tools._measure import write_json_atomic

        write_json_atomic(args.out, report, trailing_newline=False)
        print(f"wrote {args.out}")
    print(json.dumps({
        "ok": report["ok"],
        "killed_replica": report["killed_replica"],
        "killed_trace_hops": [
            h["replica"] for h in report["killed_trace"]["hops"]
        ],
        "p99_exemplar": report["p99_exemplar"]["trace_id"],
        "p99_s": report["latency"]["p99_s"],
        "invariants": report["invariants"],
    }))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
