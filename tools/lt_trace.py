"""Assemble N per-host event streams into one pod trace + imbalance report.

The pod-scale consumer of the span model (:mod:`land_trendr_tpu.obs.
spans`): give it a shared workdir (or the per-host ``events.p<i>.jsonl``
files explicitly) and it emits

* a JSON **report** on stdout — per-host wall/busy/idle-gap seconds,
  tail ratio (p95/p50 of tile compute durations), straggler and retry
  counts, span-derived stage seconds with a per-host critical path,
  plus the pod rollup: host imbalance (max wall / mean wall), pod-wide
  critical-path attribution ("if stage X were free the run would be Y%
  faster" — the estimate is ``max(wall - stage_s[X], next-binding
  stage)`` per host, max'd over hosts because the run ends with its
  last host), and the apparent wall skew removed per host;
* with ``--trace OUT.json``, a **Chrome trace-event file** of the whole
  pod on ONE offset-corrected timeline — one trace process per host,
  one thread per pipeline stage, straggler verdicts as instants.

Clock alignment: every host's ``run_start`` carries a ``(anchor_wall,
anchor_mono)`` pair sampled together; the assembler puts ``t=0`` at each
host's ``run_start``, so wall skew between hosts (bad NTP, a rebooted
peer) never shifts the trace — the distributed-init barrier means hosts
enter the run together.  The skew this removes is *reported* per host
(``wall_skew_s``), never trusted.  Caveat: genuine start stagger beyond
the barrier (sub-second) is folded into the alignment; and only each
file's LAST run scope assembles (a resumed workdir traces the current
run, not its aborted predecessor).

Exit codes: 0 ok, 2 usage/IO error (missing files / event-less workdir).

Usage:
    python tools/lt_trace.py WORKDIR | EVENTS.jsonl ... [--trace out.json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import obs_report  # noqa: E402  (the shared Chrome-trace exporter)

from land_trendr_tpu.obs.events import expand_event_paths  # noqa: E402
from land_trendr_tpu.obs.spans import assemble_pod_trace  # noqa: E402

#: report keys per host, in display order (the assembler's host summary
#: carries more — this is the imbalance view)
_HOST_KEYS = (
    "host", "process_index", "run_id", "status", "wall_skew_s", "wall_s",
    "busy_s", "idle_gap_s", "tail_ratio", "tiles_done", "pixels",
    "px_per_s", "retries", "stragglers", "tiles_leased", "tiles_stolen",
    "tiles_speculated", "stage_s", "critical_path",
)


def report_from_trace(trace: dict) -> dict:
    """The imbalance/critical-path report view of an assembled trace
    (everything except the raw span list)."""
    return {
        "files": trace["files"],
        "malformed": trace["malformed"],
        "spans": len(trace["spans"]),
        "stragglers": [
            {k: m.get(k) for k in ("tile", "t0", "duration_s", "threshold_s")}
            for m in trace["markers"]
            if m.get("name") == "straggler"
        ],
        # the elastic scheduler ACTING on those verdicts (runtime/leases)
        "steals": [
            {k: m.get(k) for k in ("tile", "t0", "host", "gen")}
            for m in trace["markers"]
            if m.get("name") == "steal"
        ],
        "speculations": [
            {k: m.get(k) for k in ("tile", "t0", "host", "gen")}
            for m in trace["markers"]
            if m.get("name") == "speculate"
        ],
        "hosts": [
            {k: h.get(k) for k in _HOST_KEYS} for h in trace["hosts"]
        ],
        "pod": trace["pod"],
    }


def trace_events(trace: dict) -> "tuple[list[dict], list[dict]]":
    """Assembled spans/markers → the ``obs_report.export_trace`` source
    shape (slices keyed by host ordinal; stage name becomes the trace
    thread), so both tools share ONE Chrome-trace writer."""
    src: "list[dict]" = []
    for s in trace["spans"]:
        src.append({
            "kind": "slice",
            "file": s["file"],
            "tid": s["name"],
            "name": f"tile {s['tile']}",
            "t0": s["t0"],
            "dur": s["dur"],
            "args": {
                k: s[k]
                for k in ("attempt", "run_id", "job_id")
                if s.get(k) is not None
            },
        })
    for m in trace["markers"]:
        src.append({
            "kind": "instant",
            "file": m["file"],
            "tid": "compute",
            # STRAGGLER / STEAL / SPECULATE instants on one timeline —
            # verdict and scheduler reaction, side by side
            "name": f"{str(m.get('name', '?')).upper()} tile {m['tile']}",
            "t0": m["t0"],
            "args": {
                k: m[k]
                for k in ("duration_s", "threshold_s", "gen")
                if m.get(k) is not None
            },
        })
    return src, trace["hosts"]


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="events.jsonl files, or workdirs containing them "
                    "(a pod workdir expands to its events.p<i>.jsonl set)")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="also export the pod-wide chrome://tracing / "
                    "Perfetto trace")
    args = ap.parse_args(argv)

    try:
        paths = expand_event_paths(args.paths)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    trace = assemble_pod_trace(paths)
    report = report_from_trace(trace)
    if args.trace:
        src, hosts = trace_events(trace)
        report["trace"] = {
            "path": args.trace,
            "events": obs_report.export_trace(src, hosts, args.trace),
        }
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
